"""Constrained decoding: JSON-schema / EBNF grammars compiled to
token-level DFAs + the budgeted device cache that serves them.

Structured output — tool calls, JSON APIs — is a workload class, not a
sampling trick: a production engine must GUARANTEE that a constrained
stream parses, at serving throughput, in the same fixed-shape compiled
batch that free streams ride. The enabling invariant is the same one
LoRA (PR 12) and the quantized page tier (PR 14) ride: per-row state
as jit *data*. Host-side, a schema compiles once into a small DFA over
the serving vocabulary; device-side, the DFA's per-state packed
allow-bitmask lives in a **grammar bank** indexed by a per-row
``(slot, state)`` id vector, and the decode program masks logits with
that row before its argmax. Admission/eviction of grammars never
recompiles anything — the serving_grammar gate counts exactly this.

Three pieces:

- the **compiler**: ``compile_schema`` (a practical JSON-schema
  subset: object/string/integer/boolean/null/enum/array) and
  ``compile_grammar`` (a regular EBNF-ish subset: literals, classes,
  ``| ( ) * + ? {m,n}``, non-recursive rule references) both lower to
  one regex AST -> Thompson NFA -> subset-construction char DFA ->
  token-level lift over a ``TokenVocab`` (a token is allowed in a
  state iff its whole surface walks the char DFA; multi-char surfaces
  advance multiple char states in one token step);
- ``GrammarStore`` — the host-resident registry of named schema
  sources, the ``AdapterStore`` shape;
- ``GrammarCache`` — the budgeted device residency manager, the
  FOURTH instance of the pool/adapter/host-arena census discipline:
  ``resident + evictable + free == n_slots - 1`` at all times, slot 0
  reserved for the all-allow identity (free rows decode through flat
  id 0 and their math is exactly the base model's), LRU retention at
  zero pins, pin-while-in-flight, atomic ``MemoryError`` refusal.
  A miss pays one priced ``grammar_compile`` on the engine clock;
  N requests sharing a schema compile it once.

State numbering inside one compiled automaton: state 0 is the
reserved all-allow self-loop (every slot's block row 0 — the identity
rows free requests index), the DFA proper starts at state 1. A row's
flat bank id is ``slot * max_states + state``.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import ledger as obs_ledger

# the character set a bounded {"type": "string"} draws from: JSON-safe
# without escapes, so the emitted text needs no backslash states
STRING_CHARS = ("abcdefghijklmnopqrstuvwxyz"
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 _-")


# ---------------------------------------------------------------------------
# token vocabulary
# ---------------------------------------------------------------------------
class TokenVocab:
    """Token id -> surface string, the lift from char DFA to token
    DFA. Token 0 is the reserved pad (empty surface, never allowed by
    any grammar); ids without a surface are non-textual (never
    allowed). ``ascii_default`` is the serving convention both the
    sim and the llama test models use: ids 1..95 are the printable
    ASCII chars ``chr(0x20 + id - 1)``, the rest of the vocabulary is
    non-textual filler."""

    def __init__(self, surfaces: Dict[int, str], vocab_size: int):
        if vocab_size < 2:
            raise ValueError("vocab_size must be >= 2")
        self.vocab_size = int(vocab_size)
        self._sur: Dict[int, str] = {}
        for tid, s in surfaces.items():
            t = int(tid)
            if not 0 < t < vocab_size:
                raise ValueError(f"token id {t} outside 1..{vocab_size - 1}"
                                 " (0 is the reserved pad)")
            if not s:
                raise ValueError(f"token {t}: empty surface")
            self._sur[t] = str(s)

    @classmethod
    def ascii_default(cls, vocab_size: int) -> "TokenVocab":
        if vocab_size < 97:
            raise ValueError(
                f"ascii_default needs vocab_size >= 97 (95 printable "
                f"chars + pad), got {vocab_size}")
        return cls({i: chr(0x20 + i - 1) for i in range(1, 96)},
                   vocab_size)

    def surface(self, token: int) -> Optional[str]:
        return self._sur.get(int(token))

    def encode(self, text: str) -> List[int]:
        """Greedy single-char encode (exact for ascii_default)."""
        rev = {s: t for t, s in self._sur.items() if len(s) == 1}
        try:
            return [rev[ch] for ch in text]
        except KeyError as e:
            raise ValueError(f"no token for char {e.args[0]!r}") from e

    def decode(self, tokens) -> str:
        """Host-side detokenization for the parse gates; non-textual
        ids render as nothing (they never appear in a constrained
        stream — the masks forbid them)."""
        return "".join(self._sur.get(int(t), "") for t in tokens)

    def items(self):
        return self._sur.items()


# ---------------------------------------------------------------------------
# regex AST -> NFA -> DFA
# ---------------------------------------------------------------------------
# AST nodes are plain tuples: ("lit", ch) / ("class", frozenset) /
# ("seq", [..]) / ("alt", [..]) / ("star", n) / ("opt", n) /
# ("plus", n) / ("rep", n, lo, hi)
def _lit_seq(text: str):
    return ("seq", [("lit", ch) for ch in text])


class _NFA:
    def __init__(self):
        self.trans: List[Dict[str, set]] = []
        self.eps: List[set] = []

    def new(self) -> int:
        self.trans.append({})
        self.eps.append(set())
        return len(self.trans) - 1

    def add(self, a: int, ch: str, b: int):
        self.trans[a].setdefault(ch, set()).add(b)

    def build(self, node) -> Tuple[int, int]:
        kind = node[0]
        if kind == "lit":
            a, b = self.new(), self.new()
            self.add(a, node[1], b)
            return a, b
        if kind == "class":
            a, b = self.new(), self.new()
            for ch in node[1]:
                self.add(a, ch, b)
            return a, b
        if kind == "seq":
            a = b = self.new()
            for sub in node[1]:
                s, e = self.build(sub)
                self.eps[b].add(s)
                b = e
            return a, b
        if kind == "alt":
            a, b = self.new(), self.new()
            for sub in node[1]:
                s, e = self.build(sub)
                self.eps[a].add(s)
                self.eps[e].add(b)
            return a, b
        if kind == "star":
            a, b = self.new(), self.new()
            s, e = self.build(node[1])
            self.eps[a].update((s, b))
            self.eps[e].update((s, b))
            return a, b
        if kind == "plus":
            return self.build(("seq", [node[1], ("star", node[1])]))
        if kind == "opt":
            a, b = self.new(), self.new()
            s, e = self.build(node[1])
            self.eps[a].update((s, b))
            self.eps[e].add(b)
            return a, b
        if kind == "rep":
            _, sub, lo, hi = node
            if not 0 <= lo <= hi:
                raise ValueError(f"bad repeat bounds {{{lo},{hi}}}")
            parts = [sub] * lo + [("opt", sub)] * (hi - lo)
            return self.build(("seq", parts))
        raise ValueError(f"unknown AST node {kind!r}")

    def closure(self, states: set) -> frozenset:
        out, todo = set(states), list(states)
        while todo:
            for nxt in self.eps[todo.pop()]:
                if nxt not in out:
                    out.add(nxt)
                    todo.append(nxt)
        return frozenset(out)


def _ast_to_char_dfa(ast):
    """-> (char transition list [state -> {ch: state}], accepting set,
    start=0). Dead states never materialize (subset construction only
    creates reachable non-empty sets)."""
    nfa = _NFA()
    s0, s1 = nfa.build(ast)
    start = nfa.closure({s0})
    ids = {start: 0}
    trans: List[Dict[str, int]] = [{}]
    todo = [start]
    while todo:
        cur = todo.pop()
        i = ids[cur]
        chars = sorted({ch for s in cur for ch in nfa.trans[s]})
        for ch in chars:
            nset = nfa.closure(
                {t for s in cur for t in nfa.trans[s].get(ch, ())})
            if nset not in ids:
                ids[nset] = len(trans)
                trans.append({})
                todo.append(nset)
            trans[i][ch] = ids[nset]
    accepting = {i for st, i in ids.items() if s1 in st}
    return trans, accepting


# ---------------------------------------------------------------------------
# the compiled artifact
# ---------------------------------------------------------------------------
def pack_masks(allow: np.ndarray) -> np.ndarray:
    """(S, V) bool -> (S, ceil(V/32)) uint32; token v lives at word
    v//32, bit v%32 — the exact unpack the decode program runs."""
    S, V = allow.shape
    words = (V + 31) // 32
    pad = np.zeros((S, words * 32), bool)
    pad[:, :V] = allow
    bits = pad.reshape(S, words, 32).astype(np.uint64)
    weights = np.uint64(1) << np.arange(32, dtype=np.uint64)
    return (bits * weights[None, None, :]).sum(-1).astype(np.uint32)


def unpack_row(row: np.ndarray, vocab_size: int) -> np.ndarray:
    """One packed (words,) uint32 row -> (V,) bool allow vector (the
    sim's host-side twin of the in-jit unpack)."""
    idx = np.arange(vocab_size)
    return ((row[idx // 32] >> (idx % 32).astype(np.uint32)) & 1) \
        .astype(bool)


@dataclasses.dataclass
class CompiledGrammar:
    """One schema's token-level automaton. ``masks`` row 0 / ``trans``
    row 0 are the reserved all-allow self-loop; the DFA proper is
    states ``1..n_states-1`` with ``start`` = 1. ``trans[s, t] == -1``
    means token ``t`` is not allowed in state ``s`` (its mask bit is
    0 too — the two encodings can never disagree: both derive from
    one walk)."""

    source: object                     # the schema dict / EBNF text
    vocab_size: int
    n_states: int                      # INCLUDING reserved state 0
    start: int
    masks: np.ndarray                  # (n_states, words) uint32
    trans: np.ndarray                  # (n_states, vocab) int32
    accepting: np.ndarray              # (n_states,) bool
    allow_counts: np.ndarray           # (n_states,) int64
    min_tokens: int
    max_tokens: Optional[int]          # None: cyclic (unbounded)

    def step(self, state: int, token: int) -> int:
        nxt = int(self.trans[int(state), int(token)])
        if nxt < 0:
            raise ValueError(
                f"token {token} not allowed in state {state} — the "
                "emitted token escaped its own mask (engine bug)")
        return nxt

    def allows(self, state: int, token: int) -> bool:
        return int(self.trans[int(state), int(token)]) >= 0

    def accepts_at(self, state: int) -> bool:
        return bool(self.accepting[int(state)])

    def masked_frac(self, state: int) -> float:
        """Fraction of the vocabulary this state's mask FORBIDS — the
        per-emission sample behind ``tokens_masked_frac``."""
        return 1.0 - float(self.allow_counts[int(state)]) \
            / self.vocab_size

    def walk(self, tokens, state: Optional[int] = None) -> int:
        s = self.start if state is None else int(state)
        for t in tokens:
            s = self.step(s, t)
        return s


def _compile_ast(ast, vocab: TokenVocab, source) -> CompiledGrammar:
    ctrans, caccept = _ast_to_char_dfa(ast)
    n_char = len(ctrans)
    V = vocab.vocab_size
    n_states = n_char + 1              # +1: reserved all-allow state 0
    trans = np.full((n_states, V), -1, np.int32)
    trans[0] = np.arange(V)            # state 0: self-loop, all allowed
    allow = np.zeros((n_states, V), bool)
    allow[0] = True
    for tid, sur in vocab.items():
        for cs in range(n_char):
            s = cs
            ok = True
            for ch in sur:
                nxt = ctrans[s].get(ch)
                if nxt is None:
                    ok = False
                    break
                s = nxt
            if ok:
                trans[cs + 1, tid] = s + 1
                allow[cs + 1, tid] = True
    accepting = np.zeros(n_states, bool)
    for a in caccept:
        accepting[a + 1] = True
    start = 1
    if not allow[start].any() and not accepting[start]:
        raise ValueError(
            "grammar allows no token from its start state under this "
            "vocabulary — the schema's alphabet has no tokens")
    # min_tokens: BFS over token steps from start to an accept
    edges = [sorted({int(n) for n in trans[s] if n >= 0})
             for s in range(n_states)]
    INF = 10 ** 9
    dist = [INF] * n_states
    dist[start] = 0
    frontier = [start]
    while frontier:
        nxt_frontier = []
        for s in frontier:
            for n in edges[s]:
                if dist[n] > dist[s] + 1:
                    dist[n] = dist[s] + 1
                    nxt_frontier.append(n)
        frontier = nxt_frontier
    reach_acc = [dist[s] for s in range(n_states)
                 if accepting[s] and dist[s] < INF]
    if not reach_acc:
        raise ValueError("grammar accepts no string reachable from "
                         "its start state under this vocabulary")
    min_tokens = min(reach_acc)
    # max_tokens: longest start->accept path when the reachable
    # subgraph is a DAG; None (unbounded) when any cycle is reachable
    max_tokens: Optional[int] = None
    order, state_mark = [], {}
    acyclic = True

    def visit(s):
        nonlocal acyclic
        stack = [(s, iter(edges[s]))]
        state_mark[s] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for n in it:
                m = state_mark.get(n)
                if m == 1:
                    acyclic = False
                elif m is None:
                    state_mark[n] = 1
                    stack.append((n, iter(edges[n])))
                    advanced = True
                    break
            if not advanced:
                state_mark[node] = 2
                order.append(node)
                stack.pop()

    visit(start)
    if acyclic:
        # longest start->s path that could still END at an accept:
        # relax in topological order (reversed post-order)
        best = {start: 0}
        mt = 0 if accepting[start] else -1
        for s in reversed(order):    # topological
            if s not in best:
                continue
            for n in edges[s]:
                d = best[s] + 1
                if best.get(n, -1) < d:
                    best[n] = d
                    if accepting[n] and d > mt:
                        mt = d
        max_tokens = mt if mt >= 0 else None
    counts = allow.sum(1).astype(np.int64)
    return CompiledGrammar(
        source=source, vocab_size=V, n_states=n_states, start=start,
        masks=pack_masks(allow), trans=trans, accepting=accepting,
        allow_counts=counts, min_tokens=int(min_tokens),
        max_tokens=max_tokens)


# ---------------------------------------------------------------------------
# JSON-schema subset -> AST
# ---------------------------------------------------------------------------
def _json_literal(v) -> str:
    return json.dumps(v, separators=(",", ":"))


def _schema_ast(schema: dict):
    if not isinstance(schema, dict):
        raise ValueError(f"schema must be a dict, got {type(schema)}")
    if "enum" in schema:
        vals = schema["enum"]
        if not vals:
            raise ValueError("enum must be non-empty")
        return ("alt", [_lit_seq(_json_literal(v)) for v in vals])
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties", {})
        parts = [("lit", "{")]
        for i, (key, sub) in enumerate(props.items()):
            if i:
                parts.append(("lit", ","))
            parts.append(_lit_seq(_json_literal(key) + ":"))
            parts.append(_schema_ast(sub))
        parts.append(("lit", "}"))
        return ("seq", parts)
    if t == "string":
        lo = int(schema.get("minLength", 0))
        hi = int(schema.get("maxLength", 8))
        if not 0 <= lo <= hi:
            raise ValueError(f"string bounds {lo}..{hi} invalid")
        cls = ("class", frozenset(STRING_CHARS))
        return ("seq", [("lit", '"'), ("rep", cls, lo, hi),
                        ("lit", '"')])
    if t == "integer":
        digits = int(schema.get("maxDigits", 3))
        if digits < 1:
            raise ValueError("maxDigits must be >= 1")
        nonzero = ("class", frozenset("123456789"))
        digit = ("class", frozenset("0123456789"))
        body = ("alt", [("lit", "0"),
                        ("seq", [nonzero,
                                 ("rep", digit, 0, digits - 1)])])
        if schema.get("minimum", -1) >= 0:
            return body
        return ("seq", [("opt", ("lit", "-")), body])
    if t == "boolean":
        return ("alt", [_lit_seq("true"), _lit_seq("false")])
    if t == "null":
        return _lit_seq("null")
    if t == "array":
        items = schema.get("items", {"type": "integer"})
        lo = int(schema.get("minItems", 1))
        hi = int(schema.get("maxItems", 3))
        if not 0 <= lo <= hi:
            raise ValueError(f"array bounds {lo}..{hi} invalid")
        sub = _schema_ast(items)
        more = ("seq", [("lit", ","), sub])
        if hi == 0:
            body = ("seq", [])
        else:
            body = ("seq", [sub, ("rep", more, max(0, lo - 1),
                                  hi - 1)])
            if lo == 0:
                body = ("opt", body)
        return ("seq", [("lit", "["), body, ("lit", "]")])
    raise ValueError(f"unsupported schema: {schema!r} (the subset: "
                     "object/string/integer/boolean/null/enum/array)")


def compile_schema(schema: dict, vocab: TokenVocab) -> CompiledGrammar:
    """JSON schema (subset) -> token-level DFA: every accepted token
    stream detokenizes to text that ``json.loads`` parses AND
    ``schema_accepts`` validates — the serving_grammar gate's claim."""
    return _compile_ast(_schema_ast(schema), vocab, schema)


def schema_accepts(schema: dict, text: str) -> bool:
    """The gate-side validator: does ``text`` parse as JSON satisfying
    the (subset) schema? One implementation shared by the bench gate
    and the tests so the two can never disagree."""
    try:
        val = json.loads(text)
    except (ValueError, TypeError):
        return False
    return _value_ok(schema, val)


def _value_ok(schema: dict, val) -> bool:
    if "enum" in schema:
        return val in schema["enum"]
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties", {})
        return (isinstance(val, dict)
                and set(val) == set(props)
                and all(_value_ok(sub, val[k])
                        for k, sub in props.items()))
    if t == "string":
        lo = int(schema.get("minLength", 0))
        hi = int(schema.get("maxLength", 8))
        return (isinstance(val, str) and lo <= len(val) <= hi
                and all(ch in STRING_CHARS for ch in val))
    if t == "integer":
        digits = int(schema.get("maxDigits", 3))
        ok = isinstance(val, int) and not isinstance(val, bool) \
            and len(str(abs(val))) <= digits
        if schema.get("minimum", -1) >= 0:
            ok = ok and val >= 0
        return ok
    if t == "boolean":
        return isinstance(val, bool)
    if t == "null":
        return val is None
    if t == "array":
        items = schema.get("items", {"type": "integer"})
        lo = int(schema.get("minItems", 1))
        hi = int(schema.get("maxItems", 3))
        return (isinstance(val, list) and lo <= len(val) <= hi
                and all(_value_ok(items, v) for v in val))
    return False


# ---------------------------------------------------------------------------
# EBNF-ish subset -> AST
# ---------------------------------------------------------------------------
class _EBNF:
    """``name ::= expr`` lines; expr = alternation of concatenations
    of postfix-quantified primaries; primaries are ``'lit'``/``"lit"``
    literals, ``[a-z0-9]`` classes, ``(...)`` groups and rule
    references. References must be NON-recursive (the subset is
    regular by construction — a recursive rule raises)."""

    def __init__(self, text: str):
        self.rules: Dict[str, str] = {}
        self.order: List[str] = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            if "::=" not in ln:
                raise ValueError(f"EBNF line without '::=': {ln!r}")
            name, rhs = ln.split("::=", 1)
            name = name.strip()
            if not name.isidentifier():
                raise ValueError(f"bad rule name {name!r}")
            if name in self.rules:
                raise ValueError(f"rule {name!r} defined twice")
            self.rules[name] = rhs.strip()
            self.order.append(name)
        if not self.rules:
            raise ValueError("empty grammar")
        self._resolving: set = set()
        self._done: Dict[str, object] = {}

    def start_ast(self):
        start = "root" if "root" in self.rules else self.order[0]
        return self.rule_ast(start)

    def rule_ast(self, name: str):
        if name in self._done:
            return self._done[name]
        if name in self._resolving:
            raise ValueError(
                f"rule {name!r} is recursive — the EBNF subset is "
                "regular (use * + ? {m,n} instead of recursion)")
        if name not in self.rules:
            raise ValueError(f"unknown rule {name!r}")
        self._resolving.add(name)
        ast, rest = self._alt(self.rules[name])
        if rest.strip():
            raise ValueError(f"rule {name!r}: trailing {rest!r}")
        self._resolving.discard(name)
        self._done[name] = ast
        return ast

    def _alt(self, s: str):
        parts, s = [], s.lstrip()
        node, s = self._seq(s)
        parts.append(node)
        while s.lstrip().startswith("|"):
            node, s = self._seq(s.lstrip()[1:])
            parts.append(node)
        return (parts[0] if len(parts) == 1 else ("alt", parts)), s

    def _seq(self, s: str):
        parts = []
        s = s.lstrip()
        while s and not s.startswith(("|", ")")):
            node, s = self._factor(s)
            parts.append(node)
            s = s.lstrip()
        if not parts:
            raise ValueError("empty alternative")
        return (parts[0] if len(parts) == 1 else ("seq", parts)), s

    def _factor(self, s: str):
        node, s = self._primary(s)
        s = s.lstrip()
        while s and s[0] in "*+?{":
            if s[0] == "*":
                node, s = ("star", node), s[1:]
            elif s[0] == "+":
                node, s = ("plus", node), s[1:]
            elif s[0] == "?":
                node, s = ("opt", node), s[1:]
            else:
                close = s.index("}")
                body = s[1:close]
                lo, _, hi = body.partition(",")
                lo = int(lo)
                hi = int(hi) if hi.strip() else lo
                node, s = ("rep", node, lo, hi), s[close + 1:]
            s = s.lstrip()
        return node, s

    def _primary(self, s: str):
        s = s.lstrip()
        if s[0] in "'\"":
            q = s[0]
            end = s.index(q, 1)
            lit = s[1:end]
            if not lit:
                raise ValueError("empty literal")
            return _lit_seq(lit), s[end + 1:]
        if s[0] == "[":
            end = s.index("]", 1)
            body, out = s[1:end], set()
            i = 0
            while i < len(body):
                if i + 2 < len(body) and body[i + 1] == "-":
                    for o in range(ord(body[i]), ord(body[i + 2]) + 1):
                        out.add(chr(o))
                    i += 3
                else:
                    out.add(body[i])
                    i += 1
            if not out:
                raise ValueError("empty character class")
            return ("class", frozenset(out)), s[end + 1:]
        if s[0] == "(":
            node, rest = self._alt(s[1:])
            rest = rest.lstrip()
            if not rest.startswith(")"):
                raise ValueError(f"unbalanced '(' near {s[:20]!r}")
            return node, rest[1:]
        i = 0
        while i < len(s) and (s[i].isalnum() or s[i] == "_"):
            i += 1
        if i == 0:
            raise ValueError(f"cannot parse near {s[:20]!r}")
        return self.rule_ast(s[:i]), s[i:]


def compile_grammar(text: str, vocab: TokenVocab) -> CompiledGrammar:
    """EBNF-ish (regular, non-recursive) grammar -> token DFA."""
    return _compile_ast(_EBNF(text).start_ast(), vocab, text)


def compile_source(source, vocab: TokenVocab) -> CompiledGrammar:
    """Dispatch on the store's value type: dict = JSON schema,
    str = EBNF text (the ``GrammarStore`` convention)."""
    if isinstance(source, dict):
        return compile_schema(source, vocab)
    if isinstance(source, str):
        return compile_grammar(source, vocab)
    raise ValueError(f"grammar source must be a schema dict or EBNF "
                     f"text, got {type(source)}")


# ---------------------------------------------------------------------------
# store + budgeted device cache
# ---------------------------------------------------------------------------
class GrammarStore:
    """Host-resident registry of named grammar sources (schema dicts
    or EBNF text) — the ``AdapterStore`` shape. Read-only at serve
    time; one store may back many engines/replicas."""

    def __init__(self, grammars: Optional[Dict[str, object]] = None):
        self._g: Dict[str, object] = {}
        for name, src in (grammars or {}).items():
            self.add(name, src)

    def add(self, name: str, source) -> None:
        if not isinstance(name, str) or not name:
            raise ValueError("grammar name must be a non-empty string")
        if name in self._g:
            raise ValueError(f"grammar {name!r} already registered")
        if not isinstance(source, (dict, str)):
            raise ValueError("grammar source must be a schema dict or "
                             "EBNF text")
        self._g[name] = source

    def get(self, name: str):
        if name not in self._g:
            raise KeyError(f"unknown grammar {name!r} (registered: "
                           f"{sorted(self._g)})")
        return self._g[name]

    def __contains__(self, name) -> bool:
        return name in self._g

    def __len__(self) -> int:
        return len(self._g)

    def names(self) -> List[str]:
        return sorted(self._g)


class GrammarCache:
    """Device residency manager for one engine's grammar bank — the
    fourth budgeted cache after the page pool, the adapter bank and
    the host arena, same census: every usable slot (slot 0 is the
    reserved all-allow identity) is exactly one of pinned-resident /
    evictable / free at all times.

    ``acquire(name, rid)`` -> ``(slot, compiled_now)``: a resident
    automaton (pinned by a sharer or parked evictable) is a HIT —
    revived, pinned, free; a miss compiles (memoized host-side — the
    DFA itself is built once per store entry ever) and uploads the
    packed masks into the bank slot through the factory hook, both
    inside ``timed`` so the engine prices one ``grammar_compile`` per
    miss on the virtual clock. ``MemoryError`` when every non-free
    slot is pinned — nothing but the refusal counter mutates.

    ``automaton(name)`` hands the engine the host-side
    ``CompiledGrammar`` (transitions, accepts, min/max tokens) for
    per-row state advance; ``flat_id(slot, state)`` is the bank row a
    decode row indexes (``slot * max_states + state``; free rows use
    0)."""

    def __init__(self, store: GrammarStore, n_slots: int,
                 max_states: int, vocab: TokenVocab,
                 init_bank: Callable[[], object],
                 upload: Callable[[object, int, object], object]):
        if n_slots < 2:
            raise ValueError("need n_slots >= 2 (slot 0 is the "
                             "reserved all-allow identity; at least "
                             "one usable slot)")
        if max_states < 2:
            raise ValueError("need max_states >= 2")
        self.store = store
        self.n_slots = int(n_slots)
        self.max_states = int(max_states)
        self.vocab = vocab
        self.bank = init_bank()
        self._upload = upload
        self._dfa: Dict[str, CompiledGrammar] = {}  # host memo
        self._slot: Dict[str, int] = {}
        self._pins: Dict[str, set] = {}
        self._evictable: Dict[str, bool] = {}  # insertion order = LRU
        self._free = list(range(self.n_slots - 1, 0, -1))
        self._stats = {"hits": 0, "misses": 0, "compiles": 0,
                       "evictions": 0, "refusals": 0}
        self._pending_compile: set = set()

    # --- probes (non-acquiring) -------------------------------------------
    def resident(self, name: str) -> bool:
        return name in self._slot

    def slot_of(self, name: str) -> Optional[int]:
        return self._slot.get(name)

    def automaton(self, name: str) -> CompiledGrammar:
        """The host-side automaton (compiling + memoizing on first
        use — NO device upload, no pin: the scheduler's min-token
        floor probes through this before admission ever runs)."""
        g = self._dfa.get(name)
        if g is None:
            g = compile_source(self.store.get(name), self.vocab)
            if g.n_states > self.max_states:
                raise ValueError(
                    f"grammar {name!r} compiles to {g.n_states} "
                    f"states > max_states {self.max_states} — raise "
                    "GrammarConfig.max_states or shrink the schema")
            self._dfa[name] = g
        return g

    def flat_id(self, slot: int, state: int) -> int:
        return int(slot) * self.max_states + int(state)

    # --- the acquire/release lifecycle ------------------------------------
    def acquire(self, name: str, rid: str, timed=None):
        """Pin ``name`` for in-flight request ``rid``; returns
        ``(slot, compiled)`` where ``compiled`` is True when the miss
        path ran (the admission paid one priced ``grammar_compile``).
        ``MemoryError`` when every non-free slot is pinned — nothing
        but the refusal counter mutates, so the caller requeues
        safely."""
        self.store.get(name)  # unknown grammars refuse loudly
        pins = self._pins.setdefault(name, set())
        if rid in pins:
            raise ValueError(f"grammar {name!r} already pinned for "
                             f"{rid!r}")
        if name in self._slot:
            self._evictable.pop(name, None)  # revival: LRU -> resident
            pins.add(rid)
            self._stats["hits"] += 1
            return self._slot[name], False
        if not self._free and not self._evictable:
            if not pins:
                self._pins.pop(name, None)  # undo the setdefault
            self._stats["refusals"] += 1
            raise MemoryError(
                f"grammar cache exhausted: {self.n_slots - 1} slots "
                f"all pinned by in-flight rows — requeue {rid!r} and "
                "retry when a row finishes")
        self._stats["misses"] += 1
        victim = None
        if self._free:
            slot = self._free.pop()
        else:
            victim = next(iter(self._evictable))
            del self._evictable[victim]
            slot = self._slot.pop(victim)
            self._pins.pop(victim, None)

        def _run():
            return self._upload(self.bank, slot, self.automaton(name))
        try:
            self.bank = timed(_run) if timed is not None else _run()
        except Exception:
            # exception-safe: a raising compile/upload (e.g. a DFA
            # larger than max_states) must not leak the slot out of
            # the census — restore the bookkeeping exactly (an
            # evicted victim's content was never overwritten)
            if victim is None:
                self._free.append(slot)
            else:
                self._slot[victim] = slot
                self._evictable[victim] = True
            self._stats["misses"] -= 1
            if not pins:
                self._pins.pop(name, None)
            raise
        if victim is not None:
            self._stats["evictions"] += 1
        self._stats["compiles"] += 1
        self._slot[name] = slot
        pins.add(rid)
        return slot, True

    def release(self, name: str, rid: str) -> None:
        """Unpin; the last unpin RETAINS the automaton (evictable
        LRU, content live) — the next sharer hits."""
        pins = self._pins.get(name)
        if pins is None or rid not in pins:
            raise ValueError(f"release: {name!r} holds no pin for "
                             f"{rid!r}")
        pins.discard(rid)
        if not pins:
            self._pins.pop(name, None)
            if name in self._slot:
                self._evictable[name] = True

    def note_rollback(self, name: str, rid: str,
                      compiled: bool) -> None:
        """``rid``'s admission failed AFTER ``acquire`` (page-pool
        refusal): unpin, and when that acquire paid the compile,
        remember the rid so ``took_compile`` attributes it to the
        admission that eventually succeeds."""
        self.release(name, rid)
        if compiled:
            self._pending_compile.add(rid)

    def forget_pending(self, rid: str) -> None:
        self._pending_compile.discard(rid)

    def took_compile(self, rid: str, compiled: bool) -> bool:
        if rid in self._pending_compile:
            self._pending_compile.discard(rid)
            return True
        return compiled

    # --- census ------------------------------------------------------------
    def resident_count(self) -> int:
        return len(self._slot)

    def populations(self) -> Tuple[int, int, int]:
        """The census populations (pinned, evictable, free) — shared
        between ``census_ok`` and the cost ledger's occupancy
        sampler."""
        pinned = sum(1 for n in self._slot if self._pins.get(n))
        return pinned, len(self._evictable), len(self._free)

    def pin_owners(self) -> Dict[str, List[str]]:
        """schema name -> sorted holder rids, pinned slots only — the
        attribution view the cost ledger splits slot-turns by."""
        return {n: sorted(self._pins[n]) for n in self._slot
                if self._pins.get(n)}

    def census_ok(self) -> bool:
        return obs_ledger.census_balanced(self.n_slots - 1,
                                          *self.populations())

    def cache_stats(self) -> dict:
        """The ``AdapterCache.cache_stats`` shape, grammar-named."""
        pinned = sum(1 for n in self._slot if self._pins.get(n))
        hits, misses = self._stats["hits"], self._stats["misses"]
        lookups = hits + misses
        return {
            "n_slots": self.n_slots - 1,
            "resident_slots": pinned,
            "evictable_slots": len(self._evictable),
            "free_slots": len(self._free),
            "resident_grammars": len(self._slot),
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
            "compiles": self._stats["compiles"],
            "evictions": self._stats["evictions"],
            "refusals": self._stats["refusals"],
        }
