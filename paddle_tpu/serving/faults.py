"""Deterministic fault injection for the serving cluster: seeded,
replayable failure schedules on the shared virtual clock.

A cluster that only knows *graceful* drain has never been tested
against the failures heavy traffic guarantees. This module is the
schedule half of the fault-tolerance layer (``cluster.ClusterRouter``
owns detection + failover, ``engine.EngineSession`` the teardown):

- ``FaultEvent``: one scheduled failure on the cluster's virtual
  timeline —

  ============ =========================================================
  crash        the replica process dies at ``t``: its in-flight rows
               are lost mid-decode, its pool (and every retained
               prefix page) is gone, and it goes SILENT — unlike a
               drain it hands nothing back; the router's heartbeat
               detector must notice the silence and fail its work over
  stall        the replica stops advancing for ``duration`` clock
               units (a GC pause / preemption / slow disk): it still
               answers health probes — the detector must NOT declare
               it dead — but every queued and in-flight request eats
               the delay
  decode_error an exception inside one decode slot at ``t``: the
               OLDEST in-flight row on the replica is torn down (pages
               freed, slot released, survivors untouched) and the
               request fails over; picking the oldest row makes a
               seeded plan deterministic without naming rids that may
               never be in flight
  ============ =========================================================

- ``FaultPlan``: an ordered list of events, JSONL round-tripped like
  traces (``save``/``load``), so one chaos incident replays
  bit-identically anywhere.
- ``synthesize_fault_plan``: one seeded crash+stall+decode-error
  schedule (the chaos gate's 1-of-N-replicas-crashing shape).
- ``FailoverConfig``: the detector/retry policy knobs — heartbeat
  cadence and timeout, per-request retry budget, exponential backoff.

The plan is pure data: replaying the same trace with the same plan and
config yields byte-identical cluster results, which is what lets
``bench_gate.py serving`` gate chaos claims (zero lost/duplicated
requests, token parity vs the fault-free run, goodput floor) instead
of anecdotes.
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

KINDS = ("crash", "stall", "decode_error")

# the SLO severity each observed fault auto-opens its incident at
# (obs.slo conventions: "page" wakes a human, "warn" files a ticket).
# A crash pages — capacity is gone and work is in flight; a stall or
# a single-slot decode error degrades service but self-heals, so it
# warns; a failover (the detector's conclusion after a crash) pages
# because it is the moment the cluster actually lost redundancy.
FAULT_SEVERITY = {"crash": "page", "stall": "warn",
                  "decode_error": "warn", "failover": "page",
                  "retry_exhausted": "page", "handoff_failed": "page"}


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled failure. ``t`` is virtual clock time; ``replica``
    names the target (the ``r<i>`` names ``ClusterRouter`` spawns, or
    a joined replica's name); ``duration`` is required for stalls and
    meaningless otherwise."""

    t: float
    kind: str
    replica: str
    duration: Optional[float] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"fault kind {self.kind!r}: use one of "
                             f"{KINDS}")
        if self.kind == "stall":
            if self.duration is None or self.duration <= 0:
                raise ValueError("a stall needs duration > 0")
        elif self.duration is not None:
            raise ValueError(f"{self.kind} takes no duration")
        if self.t < 0:
            raise ValueError("fault time must be >= 0")

    def to_json(self) -> dict:
        d = {"t": self.t, "kind": self.kind, "replica": self.replica}
        if self.duration is not None:
            d["duration"] = self.duration
        return d

    @staticmethod
    def from_json(d: dict) -> "FaultEvent":
        return FaultEvent(t=float(d["t"]), kind=str(d["kind"]),
                          replica=str(d["replica"]),
                          duration=d.get("duration"))


class FaultPlan:
    """An ordered failure schedule. Iterable; events are kept sorted
    by (t, kind, replica) so a plan built from any event order replays
    identically."""

    def __init__(self, events: Sequence[FaultEvent] = ()):
        evs = list(events)
        for e in evs:
            if not isinstance(e, FaultEvent):
                raise ValueError("FaultPlan takes FaultEvent items")
        self.events: List[FaultEvent] = sorted(
            evs, key=lambda e: (e.t, KINDS.index(e.kind), e.replica))
        crashes: dict = {}
        for e in self.events:
            if e.replica in crashes:
                raise ValueError(
                    f"{e.kind} targets {e.replica!r} at t={e.t} after "
                    f"its crash at t={crashes[e.replica]} — a dead "
                    "replica cannot fail again")
            if e.kind == "crash":
                crashes[e.replica] = e.t

    def __iter__(self):
        return iter(self.events)

    def __len__(self):
        return len(self.events)

    def crashes(self) -> List[FaultEvent]:
        return [e for e in self.events if e.kind == "crash"]

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            for e in self.events:
                f.write(json.dumps(e.to_json()) + "\n")
        return path

    @staticmethod
    def load(path: str) -> "FaultPlan":
        out = []
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    out.append(FaultEvent.from_json(json.loads(ln)))
        return FaultPlan(out)


@dataclasses.dataclass(frozen=True)
class FailoverConfig:
    """Detector + retry policy for ``ClusterRouter``.

    The heartbeat probe runs OUT OF BAND on the virtual timeline: a
    live replica (stalled or not — stall is a liveness-preserving
    fault) answers every probe; a crashed replica goes silent, and
    after ``heartbeat_timeout`` units of silence the router declares
    it dead and fails its queued + in-flight work over. Probe ticks
    every ``heartbeat_interval`` bound the detection latency to
    ``timeout + interval`` even when no request arrives.

    A failed-over request is re-placed with exponential backoff
    (``backoff_base * backoff_mult**(attempt-1)`` after the failure)
    and at most ``retry_budget`` re-placements; a request that exhausts
    the budget is recorded as FAILED — accounted exactly once, never
    silently lost."""

    heartbeat_interval: float = 2.0
    heartbeat_timeout: float = 6.0
    retry_budget: int = 3
    backoff_base: float = 1.0
    backoff_mult: float = 2.0

    def __post_init__(self):
        if self.heartbeat_interval <= 0 or self.heartbeat_timeout <= 0:
            raise ValueError("heartbeat interval/timeout must be > 0")
        if self.retry_budget < 0:
            raise ValueError("retry_budget must be >= 0")
        if self.backoff_base < 0 or self.backoff_mult < 1.0:
            raise ValueError("backoff_base must be >= 0 and "
                             "backoff_mult >= 1.0")

    def backoff(self, attempt: int) -> float:
        """Delay before re-placement number ``attempt`` (1-based)."""
        return self.backoff_base * self.backoff_mult ** max(
            0, attempt - 1)


def synthesize_fault_plan(seed: int = 0, *, replicas: Sequence[str],
                          span: float, n_crashes: int = 1,
                          n_stalls: int = 2,
                          stall_duration: Tuple[float, float]
                          = (5.0, 20.0),
                          n_decode_errors: int = 2,
                          crash_window: Tuple[float, float]
                          = (0.35, 0.65)) -> FaultPlan:
    """One seeded chaos schedule over ``span`` clock units of trace:
    ``n_crashes`` replicas die inside ``crash_window`` (fractions of
    the span — mid-trace, where in-flight and queued work is richest),
    ``n_stalls`` transient stalls and ``n_decode_errors`` slot
    exceptions land on SURVIVING replicas at uniform times. Same
    (seed, knobs) -> same plan, every field."""
    reps = list(replicas)
    if n_crashes >= len(reps):
        raise ValueError("at least one replica must survive the plan")
    if not 0.0 <= crash_window[0] < crash_window[1] <= 1.0:
        raise ValueError("crash_window must be an increasing fraction "
                         "pair in [0, 1]")
    rng = np.random.default_rng(seed)
    victims = [reps[int(i)] for i in
               rng.choice(len(reps), n_crashes, replace=False)]
    survivors = [r for r in reps if r not in victims]
    events: List[FaultEvent] = []
    for v in victims:
        t = span * float(rng.uniform(*crash_window))
        events.append(FaultEvent(t=round(t, 6), kind="crash",
                                 replica=v))
    for _ in range(n_stalls):
        r = survivors[int(rng.integers(len(survivors)))]
        t = span * float(rng.uniform(0.1, 0.9))
        d = float(rng.uniform(*stall_duration))
        events.append(FaultEvent(t=round(t, 6), kind="stall",
                                 replica=r, duration=round(d, 6)))
    for _ in range(n_decode_errors):
        r = survivors[int(rng.integers(len(survivors)))]
        t = span * float(rng.uniform(0.1, 0.9))
        events.append(FaultEvent(t=round(t, 6), kind="decode_error",
                                 replica=r))
    return FaultPlan(events)
