"""Multi-replica serving cluster: a prefix-aware router over N engines.

One ``ServingEngine`` is a building block; a service at
millions-of-users scale is N of them behind a front door that decides
WHERE each request runs. ``ClusterRouter`` is that front door, built
from pieces already in the repo:

- each replica is an ``EngineSession`` (its own engine, paged pool and
  QoS scheduler) on its own lane of one shared virtual timeline — the
  router advances EVERY lane to each arrival before placing it, so
  placement probes answer "as of now", not "as of whenever that
  replica last ran";
- **placement policies** (pluggable, ``place(request, replicas)``):

  ============== ========================================================
  round_robin    rotate over admitting replicas — the baseline every
                 cluster claim is measured against
  least_loaded   fewest queued + in-flight requests (the same live
                 queue-depth signal the obs gauges export), replica
                 index breaking ties
  prefix_aware   probe every replica's paged pool with the
                 NON-ACQUIRING ``match_prefix`` and send a request to
                 the replica already holding >= threshold tokens of its
                 prompt (ties: least loaded); below threshold, fall
                 back to least_loaded. PR 5's cache-aware co-scheduling
                 generalized ACROSS replicas: sharers concentrate where
                 their prefix is resident instead of re-prefilling it
                 N times and thrashing every pool's retention LRU
  ============== ========================================================

- **lifecycle**: ``drain`` stops admission, hands the replica's
  queued-but-never-admitted backlog back to the router for placement
  on surviving replicas (requeued requests keep their original arrival
  — the queueing they suffered stays on their record — and are counted
  exactly ONCE cluster-wide), lets in-flight rows stream to
  completion, then retires the replica (its pool census must balance
  with zero resident pages at removal). ``join`` adds a cold replica
  mid-trace; placement starts steering traffic to it immediately
  (least-loaded finds it empty, prefix-aware falls back until its pool
  warms).

The router itself never touches tokens: placement is bookkeeping, each
replica's engine does exactly what a lone engine does, and every
request's greedy stream therefore agrees token-for-token with any
other placement's (and a single big engine's) on their common length —
stream LENGTHS may differ where policy-dependent timeouts, degradation
tiers or sheds truncate, the TOKENS may not. That overlap parity (with
its coverage counts) is the cluster bench's correctness gate.

``tools/serving_workload_bench.py --cluster`` replays the ~10^5-request
``synthesize_cluster_trace`` through all three policies over
``serving.sim`` replicas; ``tools/bench_gate.py serving`` gates the
``serving_cluster`` family (prefix_aware goodput >= 1.15x round_robin
with fairness held, strictly more prefill saved, parity, and the
drain/join conservation invariant).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .engine import EngineSession, ServeResult, ServingEngine
from .metrics import _pct, goodput_tokens, jain_fairness
from .workload import Request


class PlacementPolicy:
    """Chooses the replica one arriving request runs on. ``replicas``
    is the ADMITTING subset, creation order; return one of them. A
    policy may keep state (round-robin's rotation) — one policy
    instance serves one ``ClusterRouter.run``."""

    name = "base"

    def place(self, r: Request, replicas: List["_Replica"]) -> "_Replica":
        raise NotImplementedError


class RoundRobinPlacement(PlacementPolicy):
    name = "round_robin"

    def __init__(self):
        self._i = 0

    def place(self, r, replicas):
        rep = replicas[self._i % len(replicas)]
        self._i += 1
        return rep


def _least_loaded(replicas):
    return min(replicas, key=lambda rep: (rep.session.load(), rep.index))


class LeastLoadedPlacement(PlacementPolicy):
    name = "least_loaded"

    def place(self, r, replicas):
        return _least_loaded(replicas)


class PrefixAwarePlacement(PlacementPolicy):
    """Send sharers where their prefix lives; everyone else least
    loaded. ``threshold`` is the minimum matched-token count (page
    multiple) worth steering for — below it the cache can save at most
    a partial chunk, so load balance wins; default one page."""

    name = "prefix_aware"

    def __init__(self, threshold: Optional[int] = None):
        if threshold is not None and threshold < 1:
            raise ValueError("prefix threshold must be >= 1 token")
        self.threshold = threshold

    def place(self, r, replicas):
        probes = [(rep.session.match_prefix(r.prompt), rep)
                  for rep in replicas]
        best = max(p for p, _ in probes)
        thr = self.threshold if self.threshold is not None \
            else replicas[0].session.eng.page_size
        if best >= thr:
            return _least_loaded([rep for p, rep in probes
                                  if p == best])
        return _least_loaded(replicas)


def make_placement(spec, threshold: Optional[int] = None) \
        -> PlacementPolicy:
    if isinstance(spec, PlacementPolicy):
        return spec
    if spec == "round_robin":
        return RoundRobinPlacement()
    if spec == "least_loaded":
        return LeastLoadedPlacement()
    if spec == "prefix_aware":
        return PrefixAwarePlacement(threshold)
    raise ValueError(f"placement {spec!r}: use 'round_robin', "
                     "'least_loaded', 'prefix_aware' or a "
                     "PlacementPolicy instance")


class _ReplicaTracer:
    """Track-prefixing view of one shared Tracer: replica ``name``'s
    engine events land on ``<name>/...`` tracks, so a cluster trace
    renders one lane group per replica and ``trace_report.py --json``
    can report per-replica occupancy. Engine events always stamp
    explicit times, so N per-replica virtual clocks share the tracer
    safely."""

    def __init__(self, tracer, name: str):
        self._t = tracer
        self._p = name

    def add_span(self, name, t0, dur, track="main", **attrs):
        self._t.add_span(name, t0, dur, track=f"{self._p}/{track}",
                         **attrs)

    def instant(self, name, t=None, track="main", **attrs):
        self._t.instant(name, t=t, track=f"{self._p}/{track}", **attrs)

    def counter(self, name, value, t=None, track="counters"):
        self._t.counter(name, value, t=t, track=f"{self._p}/{track}")

    def async_begin(self, name, id_, t=None, track="main", **kw):
        self._t.async_begin(name, id_, t=t,
                            track=f"{self._p}/{track}", **kw)

    def async_end(self, name, id_, t=None, track="main", **kw):
        self._t.async_end(name, id_, t=t,
                          track=f"{self._p}/{track}", **kw)

    def __getattr__(self, k):  # events/export/clear/... pass through
        return getattr(self._t, k)


class _Replica:
    __slots__ = ("name", "index", "session", "admitting", "joined_at",
                 "drained_at")

    def __init__(self, name: str, index: int, session: EngineSession,
                 joined_at: float):
        self.name = name
        self.index = index          # creation order: the tie-breaker
        self.session = session
        self.admitting = True
        self.joined_at = joined_at
        self.drained_at: Optional[float] = None


@dataclasses.dataclass
class ClusterResult:
    """One cluster replay: per-replica ServeResults plus the router's
    own ledger (placements/requeues) and lifecycle event log."""

    placement: str
    results: Dict[str, ServeResult]     # replica -> final result
    ledger: Dict[str, dict]             # rid -> {tenant, replica,
    #                                     requeues}
    events: List[dict]                  # drain/join/remove log
    trace: Optional[object] = None      # the shared Tracer, if any

    def outputs(self) -> Dict[str, List[int]]:
        """Every request's greedy stream, merged across replicas (rids
        are cluster-unique by the census invariant)."""
        out: Dict[str, List[int]] = {}
        for name in self.results:
            out.update(self.results[name].outputs)
        return out

    def census(self) -> dict:
        """The no-request-lost-or-duplicated invariant, per tenant:
        every routed rid finished OR shed on EXACTLY one replica, and
        ``completed + shed == arrived`` for each tenant. Also folds in
        each replica's pool census (``invariant_ok``) and, for retired
        replicas, the at-removal census the router recorded."""
        seen: Dict[str, str] = {}
        dup: List[str] = []
        per: Dict[str, dict] = {}

        def bump(tenant, key):
            t = tenant if tenant is not None else "_none"
            per.setdefault(t, {"arrived": 0, "completed": 0,
                               "shed": 0})[key] += 1

        for rid, led in self.ledger.items():
            bump(led["tenant"], "arrived")
        for name, res in self.results.items():
            for rid in res.outputs:
                if rid in seen:
                    dup.append(rid)
                seen[rid] = name
                bump(self.ledger[rid]["tenant"], "completed")
            for rid in res.shed:
                if rid in seen:
                    dup.append(rid)
                seen[rid] = name
                bump(self.ledger[rid]["tenant"], "shed")
        lost = sorted(set(self.ledger) - set(seen))
        conserved = all(v["completed"] + v["shed"] == v["arrived"]
                        for v in per.values())
        pools_ok = all(res.cache_stats.get("invariant_ok") is True
                       for res in self.results.values())
        removal_ok = all(e.get("census_ok", True) for e in self.events)
        return {"tenants": per,
                "duplicated": sorted(set(dup)), "lost": lost,
                "conserved": bool(conserved and not dup and not lost),
                "pool_census_ok": bool(pools_ok),
                "removal_census_ok": bool(removal_ok),
                "requeued": sum(1 for led in self.ledger.values()
                                if led["requeues"])}

    def report(self, tenant_weights: Optional[Dict[str, float]] = None) \
            -> dict:
        """The cluster rollup: per-replica ``report()`` blocks reduced
        to cluster goodput, TTFT/TPOT percentiles, per-tenant Jain
        fairness (the SAME ``jain_fairness``/``goodput_tokens``
        helpers the per-run QoS block uses) and per-replica prefix hit
        rates."""
        rows: List[dict] = []
        for name in self.results:
            for v in self.results[name].metrics.request_rows():
                v["replica"] = name
                rows.append(v)
        done = [v for v in rows if v["finish"] is not None]
        shed = [v for v in rows if v["shed"]]
        ttfts = [v["ttft"] for v in done if v["ttft"] is not None]
        tpots = [v["tpot"] for v in done if v["tpot"] is not None]
        arrivals = [v["arrival"] for v in rows]
        finishes = [v["finish"] for v in done]
        makespan = (max(finishes) - min(arrivals)) \
            if finishes and arrivals else 0.0
        tokens = sum(v["n_tokens"] for v in done)
        good = goodput_tokens(done)
        rec: dict = {
            "placement": self.placement,
            "replicas": len(self.results),
            "arrived": len(rows),
            "completed": len(done),
            "shed": len(shed),
            "shed_rate": round(len(shed) / len(rows), 4) if rows
            else 0.0,
            "generated_tokens": tokens,
            "makespan": round(makespan, 6),
            "tokens_per_sec": round(tokens / makespan, 4)
            if makespan > 0 else None,
            "goodput_tokens": good,
            "goodput_tokens_per_sec": round(good / makespan, 4)
            if makespan > 0 else None,
            "ttft_p50": _pct(ttfts, 50), "ttft_p95": _pct(ttfts, 95),
            "tpot_p50": _pct(tpots, 50), "tpot_p95": _pct(tpots, 95),
        }
        with_dl = [v for v in done if v["deadline_ms"] is not None]
        if with_dl:
            rec["slo_deadline_attained"] = round(
                sum(1 for v in with_dl if v["deadline_met"])
                / len(with_dl), 4)
        tenants = sorted({v["tenant"] for v in rows
                          if v["tenant"] is not None})
        if tenants:
            w = tenant_weights or {}
            per: Dict[str, dict] = {}
            xs = []
            for t in tenants:
                tv = [v for v in rows if v["tenant"] == t]
                gtok = goodput_tokens([v for v in tv
                                       if v["finish"] is not None])
                per[t] = {"arrived": len(tv),
                          "shed": sum(1 for v in tv if v["shed"]),
                          "completed": sum(1 for v in tv
                                           if v["finish"] is not None),
                          "goodput_tokens": gtok}
                xs.append(gtok / float(w.get(t, 1.0)))
            rec["tenants"] = per
            rec["fairness_jain"] = jain_fairness(xs)
        per_rep: Dict[str, dict] = {}
        saved_total = 0
        prefill_total = 0
        for name in sorted(self.results):
            res = self.results[name]
            rrep = res.report()
            saved = int(rrep.get("prefill_tokens_saved", 0))
            saved_total += saved
            prefill_total += res.prefill_tokens
            per_rep[name] = {
                "completed": rrep["completed"],
                "shed": len(res.shed),
                "prefill_tokens": res.prefill_tokens,
                "prefill_tokens_saved": saved,
                "prefix_hit_tokens": sum(res.prefix_cached.values()),
                "prefix_hit_rate": res.cache_stats.get("hit_rate"),
                "census_ok": res.cache_stats.get("invariant_ok"),
                "drained": any(e.get("replica") == name
                               and e.get("event") == "drain"
                               for e in self.events),
            }
        rec["prefill_tokens"] = prefill_total
        rec["prefill_tokens_saved"] = saved_total
        rec["per_replica"] = per_rep
        rec["lifecycle_events"] = len(self.events)
        return rec


class ClusterRouter:
    """N engine replicas, one placement seam, one shared virtual
    timeline.

    ``spawn(name) -> ServingEngine`` builds one replica's engine (its
    OWN serving factory — factories share live pool buffers, so two
    replicas over one factory would corrupt each other's K/V; the sim
    factory makes this cheap at any scale). ``run(trace, events)``
    replays one arrival-ordered trace, advancing every replica's lane
    to each arrival/lifecycle time before acting, so placement probes
    (load, prefix match) are causally honest. A router runs ONCE —
    build a fresh one per replay (determinism: same trace + events +
    policy -> byte-identical ClusterResult).

    ``events`` schedules lifecycle transitions deterministically:
    ``[(t, "drain", name), (t, "join", name)]``; joins sort before
    drains at equal ``t`` so a drain's requeued backlog can land on
    the replica that just joined.
    """

    def __init__(self, spawn, n_replicas: int = 2, *,
                 placement="prefix_aware",
                 prefix_threshold: Optional[int] = None,
                 trace=None):
        if not callable(spawn):
            raise ValueError("spawn must be callable: name -> "
                             "ServingEngine (one engine+factory per "
                             "replica)")
        if n_replicas < 1:
            raise ValueError("need >= 1 replica")
        self._spawn = spawn
        self.n_replicas = n_replicas
        self.placement = make_placement(placement, prefix_threshold)
        self._trace_spec = trace
        self._tracer: Optional[obs_trace.Tracer] = None
        self.replicas: List[_Replica] = []
        self.results: Dict[str, ServeResult] = {}
        self.ledger: Dict[str, dict] = {}
        self.events_log: List[dict] = []
        self._next_index = 0
        self._expect_churn = False
        self._ran = False
        self._g_load = obs_metrics.REGISTRY.gauge

    # --- lifecycle --------------------------------------------------------
    def _add_replica(self, name: str, t: float) -> _Replica:
        if any(rep.name == name for rep in self.replicas):
            raise ValueError(f"replica {name!r} already live")
        if name in self.results:
            # a retired name's ServeResult is already banked; reusing
            # it would overwrite that history and read as lost
            # requests in census() — force a fresh name instead
            raise ValueError(f"replica {name!r} already served and "
                             "retired this run — join under a fresh "
                             "name")
        eng = self._spawn(name)
        if not isinstance(eng, ServingEngine):
            raise ValueError(f"spawn({name!r}) returned "
                             f"{type(eng).__name__}, not a "
                             "ServingEngine")
        tr = _ReplicaTracer(self._tracer, name) \
            if self._tracer is not None else None
        sess = eng.session(tracer=tr, replica=name,
                           expect_churn=self._expect_churn)
        sess.clock.advance_to(t)   # a joiner starts life at NOW
        rep = _Replica(name, self._next_index, sess, joined_at=t)
        self._next_index += 1
        self.replicas.append(rep)
        self._g_load("cluster_replica_load",
                     "queued + in-flight requests on a replica",
                     replica=name).set(0.0)
        return rep

    def _rep(self, name: str) -> _Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise ValueError(f"no live replica {name!r}")

    def _join(self, name: str, t: float):
        self._add_replica(name, t)
        self.events_log.append({"t": round(t, 6), "event": "join",
                                "replica": name})
        if self._tracer is not None:
            self._tracer.instant("join", t=t, track="cluster",
                                 replica=name)

    def _drain(self, name: str, t: float):
        rep = self._rep(name)
        if not rep.admitting:
            raise ValueError(f"replica {name!r} is already draining")
        rep.admitting = False
        rep.drained_at = t
        rep.session.more_expected = False
        pulled = rep.session.pull_unadmitted()
        self.events_log.append({"t": round(t, 6), "event": "drain",
                                "replica": name,
                                "requeued": [r.rid for r in pulled],
                                "in_flight": len(rep.session.active)})
        if self._tracer is not None:
            self._tracer.instant("drain", t=t, track="cluster",
                                 replica=name, requeued=len(pulled))
        for r in pulled:
            self.ledger[r.rid]["requeues"] += 1
            self._place(r, requeue=True)
        self._maybe_retire(rep)

    def _maybe_retire(self, rep: _Replica):
        """A draining replica whose in-flight rows have all finished
        leaves the cluster; its pool census must balance with ZERO
        resident pages (every sequence freed) at removal."""
        if rep.admitting or rep.session.active or rep.session.queued():
            return
        res = rep.session.finish()
        cs = res.cache_stats
        ok = bool(cs.get("invariant_ok")
                  and cs.get("resident_pages") == 0)
        self.results[rep.name] = res
        self.replicas.remove(rep)
        self._g_load("cluster_replica_load",
                     "queued + in-flight requests on a replica",
                     replica=rep.name).set(0.0)
        self.events_log.append({
            "t": round(rep.session.clock.now(), 6), "event": "remove",
            "replica": rep.name, "census_ok": ok,
            "resident_pages": cs.get("resident_pages")})
        if self._tracer is not None:
            self._tracer.instant("remove", t=rep.session.clock.now(),
                                 track="cluster", replica=rep.name,
                                 census_ok=ok)

    # --- placement --------------------------------------------------------
    def _place(self, r: Request, requeue: bool = False):
        cands = [rep for rep in self.replicas if rep.admitting]
        if not cands:
            raise RuntimeError(
                f"no admitting replica for {r.rid} — drained the whole "
                "cluster with work still arriving")
        rep = self.placement.place(r, cands)
        rep.session.submit(r)
        led = self.ledger.get(r.rid)
        if led is None:
            self.ledger[r.rid] = {"tenant": r.tenant,
                                  "replica": rep.name, "requeues": 0}
        else:
            led["replica"] = rep.name
        # refresh EVERY admitting replica's gauge, not just the chosen
        # one — a replica that drains its backlog between placements
        # must not export its stale last-placement load
        for rep2 in cands:
            self._g_load("cluster_replica_load",
                         "queued + in-flight requests on a replica",
                         replica=rep2.name).set(
                float(rep2.session.load()))

    # --- the replay -------------------------------------------------------
    def run(self, trace: List[Request], events=()) -> ClusterResult:
        if self._ran:
            raise RuntimeError("a ClusterRouter runs once — build a "
                               "fresh router per replay")
        self._ran = True
        self._expect_churn = any(r.cancel_after is not None
                                 for r in trace)
        spec = self._trace_spec
        if spec is not None and spec is not False:
            if isinstance(spec, obs_trace.Tracer):
                self._tracer = spec
                self._tracer.clear()
            else:
                self._tracer = obs_trace.Tracer()
        timeline: List[tuple] = []
        for i, ev in enumerate(events):
            t, op, name = ev
            if op not in ("drain", "join"):
                raise ValueError(f"lifecycle event {op!r}: use 'drain' "
                                 "or 'join'")
            timeline.append((float(t), 0 if op == "join" else 1, i,
                             (op, name)))
        for i, r in enumerate(sorted(trace,
                                     key=lambda r: (r.arrival, r.rid))):
            timeline.append((r.arrival, 2, i, r))
        timeline.sort(key=lambda x: (x[0], x[1], x[2]))

        prev_tr = obs_trace.active()
        if self._tracer is not None:
            obs_trace.activate(self._tracer)
        try:
            for i in range(self.n_replicas):
                self._add_replica(f"r{i}", 0.0)
            for t, _, _, item in timeline:
                for rep in list(self.replicas):
                    rep.session.advance_until(t)
                    if not rep.admitting:
                        self._maybe_retire(rep)
                if isinstance(item, tuple):
                    op, name = item
                    (self._join if op == "join" else self._drain)(
                        name, t)
                else:
                    self._place(item)
            for rep in list(self.replicas):
                rep.session.more_expected = False
            for rep in list(self.replicas):
                self.results[rep.name] = rep.session.finish()
                if not rep.admitting:
                    # retire bookkeeping for replicas that were still
                    # streaming when the trace ran out
                    cs = self.results[rep.name].cache_stats
                    self.events_log.append({
                        "t": round(rep.session.clock.now(), 6),
                        "event": "remove", "replica": rep.name,
                        "census_ok": bool(
                            cs.get("invariant_ok")
                            and cs.get("resident_pages") == 0),
                        "resident_pages": cs.get("resident_pages")})
                self.replicas.remove(rep)
        finally:
            if self._tracer is not None:
                if prev_tr is not None:
                    obs_trace.activate(prev_tr)
                else:
                    obs_trace.deactivate()
        if self._tracer is not None and isinstance(spec, str):
            self._tracer.export(spec)
        return ClusterResult(placement=self.placement.name,
                             results=self.results, ledger=self.ledger,
                             events=self.events_log,
                             trace=self._tracer)
