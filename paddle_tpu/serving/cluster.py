"""Multi-replica serving cluster: a prefix-aware router over N engines.

One ``ServingEngine`` is a building block; a service at
millions-of-users scale is N of them behind a front door that decides
WHERE each request runs. ``ClusterRouter`` is that front door, built
from pieces already in the repo:

- each replica is an ``EngineSession`` (its own engine, paged pool and
  QoS scheduler) on its own lane of one shared virtual timeline — the
  router advances EVERY lane to each arrival before placing it, so
  placement probes answer "as of now", not "as of whenever that
  replica last ran";
- **placement policies** (pluggable, ``place(request, replicas)``):

  ============== ========================================================
  round_robin    rotate over admitting replicas — the baseline every
                 cluster claim is measured against
  least_loaded   fewest queued + in-flight requests (the same live
                 queue-depth signal the obs gauges export), replica
                 index breaking ties
  prefix_aware   probe every replica's paged pool with the
                 NON-ACQUIRING ``match_prefix`` and send a request to
                 the replica already holding >= threshold tokens of its
                 prompt (ties: least loaded); below threshold, fall
                 back to least_loaded. PR 5's cache-aware co-scheduling
                 generalized ACROSS replicas: sharers concentrate where
                 their prefix is resident instead of re-prefilling it
                 N times and thrashing every pool's retention LRU
  ============== ========================================================

- **lifecycle**: ``drain`` stops admission, hands the replica's
  queued-but-never-admitted backlog back to the router for placement
  on surviving replicas (requeued requests keep their original arrival
  — the queueing they suffered stays on their record — and are counted
  exactly ONCE cluster-wide), lets in-flight rows stream to
  completion, then retires the replica (its pool census must balance
  with zero resident pages at removal). ``join`` adds a cold replica
  mid-trace; placement starts steering traffic to it immediately
  (least-loaded finds it empty, prefix-aware falls back until its pool
  warms).

The router itself never touches tokens: placement is bookkeeping, each
replica's engine does exactly what a lone engine does, and every
request's greedy stream therefore agrees token-for-token with any
other placement's (and a single big engine's) on their common length —
stream LENGTHS may differ where policy-dependent timeouts, degradation
tiers or sheds truncate, the TOKENS may not. That overlap parity (with
its coverage counts) is the cluster bench's correctness gate.

``tools/serving_workload_bench.py --cluster`` replays the ~10^5-request
``synthesize_cluster_trace`` through all three policies over
``serving.sim`` replicas; ``tools/bench_gate.py serving`` gates the
``serving_cluster`` family (prefix_aware goodput >= 1.15x round_robin
with fairness held, strictly more prefill saved, parity, and the
drain/join conservation invariant).
"""
from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional

from ..obs import flight as obs_flight
from ..obs import ledger as obs_ledger
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from .autoscale import Autoscaler
from .engine import (EngineSession, KVHandoff, ServeResult,
                     ServingEngine, UnstampedHandoffError)
from .faults import (FAULT_SEVERITY, FailoverConfig, FaultEvent,
                     FaultPlan)
from .metrics import _pct, goodput_tokens, jain_fairness
from .workload import Request


class PlacementPolicy:
    """Chooses the replica one arriving request runs on. ``replicas``
    is the ADMITTING subset, creation order; return one of them. A
    policy may keep state (round-robin's rotation) — one policy
    instance serves one ``ClusterRouter.run``."""

    name = "base"

    def place(self, r: Request, replicas: List["_Replica"]) -> "_Replica":
        raise NotImplementedError


class RoundRobinPlacement(PlacementPolicy):
    name = "round_robin"

    def __init__(self):
        self._i = 0

    def place(self, r, replicas):
        rep = replicas[self._i % len(replicas)]
        self._i += 1
        return rep


def _least_loaded(replicas):
    return min(replicas, key=lambda rep: (rep.session.load(), rep.index))


class LeastLoadedPlacement(PlacementPolicy):
    name = "least_loaded"

    def place(self, r, replicas):
        return _least_loaded(replicas)


class PrefixAwarePlacement(PlacementPolicy):
    """Send sharers where their (cached) state lives; everyone else
    least loaded. Two residency signals, one discipline:

    - **adapter residency** (multi-model serving): a request naming a
      LoRA adapter prefers a replica whose device bank already HOLDS
      that adapter (non-acquiring ``adapter_resident`` probe; ties —
      least loaded among holders) — re-uploading a delta set on N
      replicas is exactly the thrash re-prefilling a shared prompt N
      times is, so the same placement rule covers both. Residency is
      a PREFERENCE, not a pin: when the least-loaded holder is
      already ``adapter_load_slack`` requests deeper than the
      least-loaded replica overall, the request goes there instead
      and the hot adapter REPLICATES (one more upload buys another
      replica's worth of capacity — the S-LoRA fleet behavior; a
      sticky rule would recreate the one-model-per-replica split's
      hot-spot exactly). With no holder, fall through to the
      prefix/least-loaded logic below (the chosen replica uploads
      once and becomes the holder).
    - **prefix residency**: the PR-6 rule — probe every replica's
      paged pool with the non-acquiring ``match_prefix`` and steer to
      a replica holding >= ``threshold`` tokens of the prompt (page
      multiple; default one page), ties least loaded; below
      threshold, least loaded overall."""

    name = "prefix_aware"

    def __init__(self, threshold: Optional[int] = None,
                 adapter_load_slack: Optional[int] = None):
        if threshold is not None and threshold < 1:
            raise ValueError("prefix threshold must be >= 1 token")
        if adapter_load_slack is not None and adapter_load_slack < 1:
            raise ValueError("adapter_load_slack must be >= 1 "
                             "request")
        self.threshold = threshold
        self.adapter_load_slack = adapter_load_slack

    def place(self, r, replicas):
        if r.adapter is not None:
            holders = [rep for rep in replicas
                       if rep.session.adapter_resident(r.adapter)]
            if holders:
                best_h = _least_loaded(holders)
                best_all = _least_loaded(replicas)
                slack = self.adapter_load_slack \
                    if self.adapter_load_slack is not None \
                    else max(1, replicas[0].session.eng.slots // 2)
                if best_h.session.load() \
                        <= best_all.session.load() + slack:
                    return best_h
                return best_all  # replicate the hot adapter there
        probes = [(rep.session.match_prefix(r.prompt), rep)
                  for rep in replicas]
        # the default threshold is each replica's OWN page geometry (a
        # pool publishes prefixes in its own page multiples) — the old
        # replicas[0] fallback silently mis-thresholded every other
        # member of a heterogeneous fleet; homogeneous fleets score
        # identically
        hits = [(p, rep) for p, rep in probes
                if p >= (self.threshold if self.threshold is not None
                         else rep.session.eng.page_size)]
        if hits:
            best = max(p for p, _ in hits)
            return _least_loaded([rep for p, rep in hits
                                  if p == best])
        return _least_loaded(replicas)


def _place_decode(h: KVHandoff, replicas,
                  prices=None) -> Optional["_Replica"]:
    """The decode stage's default placement: the CHEAPEST-to-import
    decode-capable replica (``prices`` maps replica name → priced
    reshard/repage/transcode cost on the virtual clock; a twin — same
    tp/geometry/codec — prices 0.0, so homogeneous fleets keep the
    pre-hetero order exactly), then the most open decode slots (slot
    availability is the decode lane's scarce resource; load then
    creation order break ties). None when no candidate is
    decode-capable."""
    cands = [rep for rep in replicas
             if rep.role in ("decode", "both")]
    if not cands:
        return None
    pr = prices or {}
    return min(cands, key=lambda rep: (pr.get(rep.name, 0.0),
                                       -rep.session.free_slot_count(),
                                       rep.session.load(), rep.index))


class DisaggregatedPlacement(PlacementPolicy):
    """DistServe/Splitwise-style phase-split placement: ADMISSIONS go
    to prefill-capable workers (role "prefill" or "both"), each
    placed where its prefill finishes soonest — the candidate's
    pending prefill-chunk backlog (queued prompts + async-lane
    remainder) plus THIS prompt's own uncached chunks via the
    non-acquiring ``match_prefix`` probe: the
    ``ServiceEstimator.prefill_cost`` arithmetic in chunk units
    (replicas share one cost table, so the unit cancels). DECODE
    placement happens per finished prefill at handoff time
    (``place_decode``): the decode-capable worker with the most open
    slots. With no roles configured every replica is "both" and this
    degrades to backlog-aware least-loaded placement (no handoffs
    ever fire)."""

    name = "disaggregated"

    def place(self, r, replicas):
        cands = [rep for rep in replicas
                 if rep.role in ("prefill", "both")] or list(replicas)

        def score(rep):
            s = rep.session
            own = -(-max(0, len(r.prompt)
                         - s.match_prefix(r.prompt)) // s.eng.chunk_C)
            # the final chunk always runs (last-position logits), so
            # even a fully-cached prompt costs one chunk
            return (s.prefill_backlog() + max(1, own), s.load(),
                    rep.index)
        return min(cands, key=score)

    @staticmethod
    def place_decode(h: KVHandoff, replicas, prices=None):
        return _place_decode(h, replicas, prices)


def make_placement(spec, threshold: Optional[int] = None) \
        -> PlacementPolicy:
    if isinstance(spec, PlacementPolicy):
        return spec
    if spec == "round_robin":
        return RoundRobinPlacement()
    if spec == "least_loaded":
        return LeastLoadedPlacement()
    if spec == "prefix_aware":
        return PrefixAwarePlacement(threshold)
    if spec == "disaggregated":
        return DisaggregatedPlacement()
    raise ValueError(f"placement {spec!r}: use 'round_robin', "
                     "'least_loaded', 'prefix_aware', "
                     "'disaggregated' or a PlacementPolicy instance")


class _ReplicaTracer:
    """Track-prefixing view of one shared Tracer: replica ``name``'s
    engine events land on ``<name>/...`` tracks, so a cluster trace
    renders one lane group per replica and ``trace_report.py --json``
    can report per-replica occupancy. Engine events always stamp
    explicit times, so N per-replica virtual clocks share the tracer
    safely."""

    def __init__(self, tracer, name: str):
        self._t = tracer
        self._p = name

    def add_span(self, name, t0, dur, track="main", **attrs):
        self._t.add_span(name, t0, dur, track=f"{self._p}/{track}",
                         **attrs)

    def instant(self, name, t=None, track="main", **attrs):
        self._t.instant(name, t=t, track=f"{self._p}/{track}", **attrs)

    def counter(self, name, value, t=None, track="counters"):
        self._t.counter(name, value, t=t, track=f"{self._p}/{track}")

    def async_begin(self, name, id_, t=None, track="main", **kw):
        self._t.async_begin(name, id_, t=t,
                            track=f"{self._p}/{track}", **kw)

    def async_end(self, name, id_, t=None, track="main", **kw):
        self._t.async_end(name, id_, t=t,
                          track=f"{self._p}/{track}", **kw)

    def __getattr__(self, k):  # events/export/clear/... pass through
        return getattr(self._t, k)


class _Replica:
    __slots__ = ("name", "index", "session", "admitting", "joined_at",
                 "drained_at", "last_seen", "role", "monitor")

    def __init__(self, name: str, index: int, session: EngineSession,
                 joined_at: float, role: str = "both"):
        self.name = name
        self.index = index          # creation order: the tie-breaker
        self.session = session
        self.admitting = True
        self.joined_at = joined_at
        self.drained_at: Optional[float] = None
        # last time this replica answered a health probe (any timeline
        # step while its session is alive); a crashed session goes
        # silent and the gap is what the failure detector reads
        self.last_seen = joined_at
        # disaggregation stage ("prefill" / "decode" / "both") — the
        # session enforces it; the placement policy reads it
        self.role = role
        # this replica's SLOMonitor (shared IncidentLog), None when
        # the router runs without an SLO config
        self.monitor = None


@dataclasses.dataclass
class ClusterResult:
    """One cluster replay: per-replica ServeResults plus the router's
    own ledger (placements/requeues/retries) and lifecycle event log.
    Under a fault plan, ``salvaged`` holds the tokens each failed-over
    request had already emitted before its replica died (the stream
    prefix its retry resumed from) and ``failed`` the requests whose
    retry budget ran out — accounted exactly once, never silently
    lost."""

    placement: str
    results: Dict[str, ServeResult]     # replica -> final result
    ledger: Dict[str, dict]             # rid -> {tenant, replica,
    #                                     requeues, retries, path}
    events: List[dict]                  # drain/join/crash/remove log
    trace: Optional[object] = None      # the shared Tracer, if any
    salvaged: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)           # rid -> pre-crash tokens
    failed: Dict[str, str] = dataclasses.field(default_factory=dict)
    # rid -> reason (retry budget exhausted / unplaceable)
    faulted: bool = False               # a fault plan ran OR the
    # failover machinery actually engaged (backend-raised DecodeErrors
    # under a failover-only config); gates the chaos report/census
    # blocks so fault-free replays keep the PR-6 records byte-for-byte
    handoffs: Dict = dataclasses.field(default_factory=dict)
    # disaggregated KV-handoff ledger {exported, imported, reclaimed,
    # failed} — empty (and absent from census/report) when no
    # prefill-role replica ever exported, so role-less replays keep
    # the PR-7 records byte-for-byte
    incidents: Optional[List] = None    # obs.slo.Incident list when
    # the router ran with slo=...; None otherwise. Deliberately NOT
    # folded into report()/census() — the obs_slo gate requires a
    # monitor-on replay's records byte-identical to monitor-off
    slo_log: Optional[object] = None    # the shared IncidentLog
    flight: Optional[object] = None     # the FlightRecorder, if any
    autoscale: Optional[dict] = None    # Autoscaler.summary() — the
    # byte-deterministic action log plus per-kind counts — when the
    # router ran with autoscale=...; None otherwise (and nothing in
    # the replay differs from a pre-autoscale router)
    replica_hours: Dict[str, dict] = dataclasses.field(
        default_factory=dict)           # name -> {joined, left, hours}
    # — the capacity-cost ledger elastic autoscaling is judged on
    # (replica-hours strictly below a static fleet at equal goodput)
    cost_rollup: Optional[dict] = None  # CostLedger.rollup() — the
    # request -> tenant -> feature attribution plus the cluster-wide
    # conservation audit — when the router ran with cost_ledger=...;
    # None otherwise (and nothing in the replay differs from a
    # pre-ledger router)
    cost_ledger: Optional[object] = None  # the shared CostLedger
    # itself (save_costs/publish live here); None when un-armed

    def save_costs(self, path: str) -> str:
        """Dump the shared cost ledger's attribution rows as JSONL
        (atomic; global conservation row LAST). Raises when the
        replay ran without ``cost_ledger=`` — there is nothing to
        save, and an empty file would read as a costless cluster."""
        if self.cost_ledger is None:
            raise ValueError("this replay ran without cost_ledger=; "
                             "no cost rows to save")
        return self.cost_ledger.save_costs(path)

    def replica_hours_total(self) -> float:
        """Summed live time across every replica that ever joined —
        the denominator of the autoscaling economics claim."""
        return round(sum(h["hours"]
                         for h in self.replica_hours.values()), 6)

    def save_actions(self, path: str) -> str:
        """Dump the autoscaler's action log as JSONL (atomic, the
        shared ``obs`` write discipline) — the artifact the
        determinism gate byte-compares across seeded replays. Raises
        when the router ran without autoscale=."""
        if self.autoscale is None:
            raise ValueError("this replay ran without an autoscaler "
                             "(ClusterRouter(autoscale=...)) — there "
                             "is no action log to save")
        import json as _json
        obs_slo._atomic_write(
            path, "".join(_json.dumps(a) + "\n"
                          for a in self.autoscale["actions"]))
        return path

    def save_incidents(self, path: str) -> str:
        """Dump the run's incident set as JSONL (atomic; loads back
        through ``obs.slo.load_incidents`` / the shared tolerant
        policy). Raises when the router ran without slo=."""
        if self.slo_log is None:
            raise ValueError("this replay ran without an SLO monitor "
                             "(ClusterRouter(slo=...)) — there is no "
                             "incident log to save")
        return self.slo_log.save(path)

    def outputs(self) -> Dict[str, List[int]]:
        """Every request's greedy stream, merged across replicas (rids
        are cluster-unique by the census invariant). A failed-over
        request's stream is its salvaged pre-crash tokens + what the
        survivor emitted after resuming — the full stream the client
        actually received, the one fault-free parity is judged on."""
        out: Dict[str, List[int]] = {}
        for name in self.results:
            out.update(self.results[name].outputs)
        for rid, pre in self.salvaged.items():
            if rid in out:
                out[rid] = list(pre) + list(out[rid])
        return out

    def census(self) -> dict:
        """The no-request-lost-or-duplicated invariant, per tenant:
        every routed rid finished, shed, OR exhausted its retry budget
        on EXACTLY one replica, and ``completed + shed + failed ==
        arrived`` for each tenant (``failed`` is nonzero only when
        the failover machinery engaged — a fault plan or a
        backend-raised DecodeError under a failover config — or when
        a disaggregated KV handoff found no decode-capable replica
        that could adopt it, the one placement failure a role-ful
        router accounts instead of crashing on). Also folds in each
        replica's pool census (``invariant_ok``) and, for retired or
        crashed replicas, the at-removal census the router recorded."""
        seen: Dict[str, str] = {}
        dup: List[str] = []
        per: Dict[str, dict] = {}

        def bump(tenant, key):
            t = tenant if tenant is not None else "_none"
            d = per.setdefault(t, {"arrived": 0, "completed": 0,
                                   "shed": 0})
            d[key] = d.get(key, 0) + 1

        for rid, led in self.ledger.items():
            bump(led["tenant"], "arrived")
        for name, res in self.results.items():
            for rid in res.outputs:
                if rid in seen:
                    dup.append(rid)
                seen[rid] = name
                bump(self.ledger[rid]["tenant"], "completed")
            for rid in res.shed:
                if rid in seen:
                    dup.append(rid)
                seen[rid] = name
                bump(self.ledger[rid]["tenant"], "shed")
        for rid in self.failed:
            if rid in seen:
                dup.append(rid)
            seen[rid] = "_failed"
            bump(self.ledger[rid]["tenant"], "failed")
        lost = sorted(set(self.ledger) - set(seen))
        conserved = all(v["completed"] + v["shed"]
                        + v.get("failed", 0) == v["arrived"]
                        for v in per.values())
        pools_ok = all(res.cache_stats.get("invariant_ok") is True
                       for res in self.results.values())
        removal_ok = all(e.get("census_ok", True) for e in self.events)
        out = {"tenants": per,
               "duplicated": sorted(set(dup)), "lost": lost,
               "conserved": bool(conserved and not dup and not lost),
               "pool_census_ok": bool(pools_ok),
               "removal_census_ok": bool(removal_ok),
               "requeued": sum(1 for led in self.ledger.values()
                               if led["requeues"])}
        if self.faulted:
            out["retried"] = sum(1 for led in self.ledger.values()
                                 if led.get("retries"))
            out["failed"] = len(self.failed)
        if self.handoffs.get("exported"):
            # the exactly-once KV-handoff balance: every exported
            # chain was imported by a decode worker, reclaimed (its
            # destination drained/crashed before adopting it — the
            # request re-placed and re-prefilled), or accounted
            # FAILED; nothing vanished in flight
            ho = dict(self.handoffs)
            ho["balanced"] = (ho["exported"] == ho["imported"]
                              + ho["reclaimed"] + ho["failed"])
            out["handoffs"] = ho
            out["conserved"] = bool(out["conserved"]
                                    and ho["balanced"])
        return out

    def report(self, tenant_weights: Optional[Dict[str, float]] = None) \
            -> dict:
        """The cluster rollup: per-replica ``report()`` blocks reduced
        to cluster goodput, TTFT/TPOT percentiles, per-tenant Jain
        fairness (the SAME ``jain_fairness``/``goodput_tokens``
        helpers the per-run QoS block uses) and per-replica prefix hit
        rates."""
        rows: List[dict] = []
        for name in self.results:
            for v in self.results[name].metrics.request_rows():
                v["replica"] = name
                rows.append(v)
        done = [v for v in rows if v["finish"] is not None]
        shed = [v for v in rows if v["shed"]]
        ttfts = [v["ttft"] for v in done if v["ttft"] is not None]
        tpots = [v["tpot"] for v in done if v["tpot"] is not None]
        arrivals = [v["arrival"] for v in rows]
        finishes = [v["finish"] for v in done]
        makespan = (max(finishes) - min(arrivals)) \
            if finishes and arrivals else 0.0
        tokens = sum(v["n_tokens"] for v in done)
        good = goodput_tokens(done)
        rec: dict = {
            "placement": self.placement,
            "replicas": len(self.results),
            "arrived": len(rows),
            "completed": len(done),
            "shed": len(shed),
            "shed_rate": round(len(shed) / len(rows), 4) if rows
            else 0.0,
            "generated_tokens": tokens,
            "makespan": round(makespan, 6),
            "tokens_per_sec": round(tokens / makespan, 4)
            if makespan > 0 else None,
            "goodput_tokens": good,
            "goodput_tokens_per_sec": round(good / makespan, 4)
            if makespan > 0 else None,
            "ttft_p50": _pct(ttfts, 50), "ttft_p95": _pct(ttfts, 95),
            "tpot_p50": _pct(tpots, 50), "tpot_p95": _pct(tpots, 95),
        }
        with_dl = [v for v in done if v["deadline_ms"] is not None]
        if with_dl:
            rec["slo_deadline_attained"] = round(
                sum(1 for v in with_dl if v["deadline_met"])
                / len(with_dl), 4)
        tenants = sorted({v["tenant"] for v in rows
                          if v["tenant"] is not None})
        if tenants:
            w = tenant_weights or {}
            per: Dict[str, dict] = {}
            xs = []
            for t in tenants:
                tv = [v for v in rows if v["tenant"] == t]
                gtok = goodput_tokens([v for v in tv
                                       if v["finish"] is not None])
                per[t] = {"arrived": len(tv),
                          "shed": sum(1 for v in tv if v["shed"]),
                          "completed": sum(1 for v in tv
                                           if v["finish"] is not None),
                          "goodput_tokens": gtok}
                xs.append(gtok / float(w.get(t, 1.0)))
            rec["tenants"] = per
            rec["fairness_jain"] = jain_fairness(xs)
        per_rep: Dict[str, dict] = {}
        saved_total = 0
        prefill_total = 0
        for name in sorted(self.results):
            res = self.results[name]
            rrep = res.report()
            saved = int(rrep.get("prefill_tokens_saved", 0))
            saved_total += saved
            prefill_total += res.prefill_tokens
            per_rep[name] = {
                "completed": rrep["completed"],
                "shed": len(res.shed),
                "prefill_tokens": res.prefill_tokens,
                "prefill_tokens_saved": saved,
                "prefix_hit_tokens": sum(res.prefix_cached.values()),
                "prefix_hit_rate": res.cache_stats.get("hit_rate"),
                "census_ok": res.cache_stats.get("invariant_ok"),
                "drained": any(e.get("replica") == name
                               and e.get("event") == "drain"
                               for e in self.events),
            }
        rec["prefill_tokens"] = prefill_total
        rec["prefill_tokens_saved"] = saved_total
        rec["per_replica"] = per_rep
        rec["lifecycle_events"] = len(self.events)
        if self.faulted:
            # the chaos block appears ONLY when a fault plan ran — a
            # fault-free replay keeps the PR-6 record byte-for-byte
            ev = [e["event"] for e in self.events]
            rec["crashes"] = ev.count("crash")
            rec["stalls"] = ev.count("stall")
            rec["decode_errors"] = ev.count("decode_error")
            rec["failovers"] = ev.count("dead")
            rec["retried_requests"] = sum(
                1 for led in self.ledger.values()
                if led.get("retries"))
            rec["resumed_with_salvage"] = len(self.salvaged)
            rec["failed_requests"] = len(self.failed)
        if self.handoffs.get("exported"):
            # only disaggregated (role-ful) replays grow this block
            rec["kv_handoffs"] = dict(self.handoffs)
            rec["handed_off_requests"] = sum(
                1 for led in self.ledger.values()
                if led.get("handoffs"))
        rec["replica_hours"] = self.replica_hours_total()
        if self.autoscale is not None:
            # only autoscaled replays grow this block
            rec["autoscale"] = {k: self.autoscale[k]
                                for k in ("joins", "drains",
                                          "drain_noops",
                                          "role_changes", "degrades")}
        return rec


class ClusterRouter:
    """N engine replicas, one placement seam, one shared virtual
    timeline.

    ``spawn(name) -> ServingEngine`` builds one replica's engine (its
    OWN serving factory — factories share live pool buffers, so two
    replicas over one factory would corrupt each other's K/V; the sim
    factory makes this cheap at any scale). ``run(trace, events)``
    replays one arrival-ordered trace, advancing every replica's lane
    to each arrival/lifecycle time before acting, so placement probes
    (load, prefix match) are causally honest. A router runs ONCE —
    build a fresh one per replay (determinism: same trace + events +
    policy -> byte-identical ClusterResult).

    ``events`` schedules lifecycle transitions deterministically:
    ``[(t, "drain", name), (t, "join", name)]``; joins sort before
    drains at equal ``t`` so a drain's requeued backlog can land on
    the replica that just joined.

    ``faults`` (a ``faults.FaultPlan``) schedules crash / stall /
    decode-error injection on the same timeline; ``failover`` (a
    ``faults.FailoverConfig``, defaulted when a plan is given) sets
    the heartbeat detector and retry/backoff policy. With
    ``faults=None`` the fault machinery is entirely inert — no probe
    ticks, no detection pass — and the replay is byte-identical to a
    fault-unaware router.
    """

    def __init__(self, spawn, n_replicas: int = 2, *,
                 placement="prefix_aware",
                 prefix_threshold: Optional[int] = None,
                 trace=None, faults: Optional[FaultPlan] = None,
                 failover: Optional[FailoverConfig] = None,
                 roles: Optional[Dict[str, str]] = None,
                 kv_transfer_unit: float = 0.0,
                 slo=None, flight=None, slo_on_incident=(),
                 autoscale: Optional[Autoscaler] = None,
                 cost_ledger=None):
        if not callable(spawn):
            raise ValueError("spawn must be callable: name -> "
                             "ServingEngine (one engine+factory per "
                             "replica)")
        if n_replicas < 1:
            raise ValueError("need >= 1 replica")
        self._spawn = spawn
        self.n_replicas = n_replicas
        self.placement = make_placement(placement, prefix_threshold)
        self._trace_spec = trace
        self._tracer: Optional[obs_trace.Tracer] = None
        self.replicas: List[_Replica] = []
        self.results: Dict[str, ServeResult] = {}
        self.ledger: Dict[str, dict] = {}
        self.events_log: List[dict] = []
        self._next_index = 0
        self._expect_churn = False
        self._ran = False
        self._g_load = obs_metrics.REGISTRY.gauge
        if faults is not None and not isinstance(faults, FaultPlan):
            faults = FaultPlan(list(faults))
        self._faults = faults
        # failover defaults alongside a fault plan; it may also be
        # passed ALONE — the retry policy for rows a backend-raised
        # DecodeError aborts without any scheduled fault
        self.failover = failover if failover is not None \
            else (FailoverConfig() if faults is not None else None)
        self._salvage: Dict[str, List[int]] = {}
        self.failed: Dict[str, str] = {}
        self._heap: List[tuple] = []
        self._seq = 0
        self._ctr_failovers = obs_metrics.REGISTRY.counter(
            "cluster_failovers_total",
            "replicas declared dead and failed over")
        # --- disaggregation (inert without roles) -------------------
        # roles: replica name -> "prefill" | "decode" | "both"
        # (unnamed replicas default to "both"). A prefill-role
        # session exports every finished prefill as a KVHandoff; the
        # router prices its delivery at kv_transfer_unit PER PAGE on
        # the shared timeline and places it on a decode worker
        # (placement.place_decode when the policy has one, most open
        # slots otherwise). With roles=None no session ever exports
        # and the replay is byte-identical to a role-unaware router.
        if roles:
            bad = {n: v for n, v in roles.items()
                   if v not in ("prefill", "decode", "both")}
            if bad:
                raise ValueError(f"roles {bad}: use 'prefill', "
                                 "'decode' or 'both'")
        self._roles = dict(roles or {})
        if kv_transfer_unit < 0:
            raise ValueError("kv_transfer_unit must be >= 0")
        self.kv_transfer_unit = float(kv_transfer_unit)
        self._handoff = {"exported": 0, "imported": 0,
                         "reclaimed": 0, "failed": 0}
        # per-axis count of handoffs TRANSFORMED on import (tp /
        # page / codec); stays empty — and absent from results — on
        # homogeneous fleets
        self._resharded: Dict[str, int] = {}
        # --- SLO watchdog (inert without slo=) ----------------------
        # slo: a sequence of obs.slo rules (may be EMPTY — fault
        # events and heartbeats still auto-open/feed incidents). The
        # router builds ONE SLOMonitor per replica over ONE shared
        # IncidentLog, so ids stay cluster-unique and open-order
        # deterministic; drain/join changes the watched membership
        # (a joiner gets a monitor at join time, a removed replica's
        # monitor retires — its silence is no longer an alert).
        # flight: a FlightRecorder, or a bundle-directory path string
        # (a recorder is built over it) — incidents then freeze
        # postmortem bundles; requires slo=. slo_on_incident:
        # callbacks delivered every incident as it opens (the QoS
        # degradation seam — e.g. a scheduler's note_incident).
        if slo is not None and isinstance(slo, obs_slo.SLOMonitor):
            raise ValueError("cluster slo= takes a RULES sequence, "
                             "not a monitor — the router builds one "
                             "monitor per replica over a shared "
                             "IncidentLog")
        self._slo_rules = None if slo is None else list(slo)
        self._slo_cbs = list(slo_on_incident)
        # --- elastic autoscaling (inert without autoscale=) ---------
        # autoscale: an autoscale.Autoscaler — the control plane that
        # ACTS on the incident stream: joins standby replicas on
        # sustained burn, drains idle ones when the budget recovers,
        # re-assigns prefill<->decode roles as the mix shifts, and
        # fans page incidents into every live QoSScheduler (tier
        # actuation). Decisions run at fixed ticks on the shared
        # timeline (plus the incident-open callback), so seeded
        # replays produce a byte-identical action log. Requires slo=
        # (the detect half of the loop); with autoscale=None nothing
        # here runs and the replay is byte-identical to a
        # pre-autoscale router.
        if autoscale is not None \
                and not isinstance(autoscale, Autoscaler):
            raise ValueError("autoscale= takes an autoscale.Autoscaler")
        if autoscale is not None and slo is None:
            raise ValueError("autoscale= needs slo= (pass a rules "
                             "sequence — even [] — so the autoscaler "
                             "has an incident stream to subscribe to)")
        self._autoscaler = autoscale
        if autoscale is not None:
            autoscale.attach()
            # subscription BEFORE the monitors copy the callback list
            self._slo_cbs.append(self._autoscale_on_incident)
        # --- cost ledger (inert without cost_ledger=) ---------------
        # cost_ledger: True builds ONE shared obs.ledger.CostLedger
        # (or pass an instance) that every spawned replica's engine
        # books against — one book per replica plus a "cluster" book
        # for router-priced kv_transfer units. A request's account is
        # SHARED across replicas, so handoff/failover/preempt move
        # its open account exactly once (accounts are keyed by rid,
        # not replica). None keeps every replay byte-identical to a
        # pre-ledger router. (Distinct from self.ledger — the
        # placement bookkeeping dict that predates cost accounting.)
        if cost_ledger is True:
            cost_ledger = obs_ledger.CostLedger()
        if cost_ledger is not None \
                and not isinstance(cost_ledger, obs_ledger.CostLedger):
            raise ValueError("cost_ledger= takes True or an "
                             "obs.ledger.CostLedger instance")
        self._cost_ledger = cost_ledger
        self._hours: Dict[str, dict] = {}
        if flight is not None and slo is None:
            raise ValueError("flight= needs slo= (bundles are written "
                             "when an SLO incident fires)")
        if isinstance(flight, str):
            flight = obs_flight.FlightRecorder(bundle_dir=flight)
        self.flight = flight
        self.slo_log: Optional[obs_slo.IncidentLog] = None
        self._mon_cluster: Optional[obs_slo.SLOMonitor] = None
        if self._slo_rules is not None:
            self.slo_log = obs_slo.IncidentLog()
            # router-scope events (a retry budget exhausting, an
            # unadoptable KV handoff) have no single replica to blame
            self._mon_cluster = obs_slo.SLOMonitor(
                [], source="cluster", log=self.slo_log,
                flight=self.flight, on_incident=self._slo_cbs)

    # --- lifecycle --------------------------------------------------------
    def _add_replica(self, name: str, t: float) -> _Replica:
        if any(rep.name == name for rep in self.replicas):
            raise ValueError(f"replica {name!r} already live")
        if name in self.results:
            # a retired name's ServeResult is already banked; reusing
            # it would overwrite that history and read as lost
            # requests in census() — force a fresh name instead
            raise ValueError(f"replica {name!r} already served and "
                             "retired this run — join under a fresh "
                             "name")
        eng = self._spawn(name)
        if not isinstance(eng, ServingEngine):
            raise ValueError(f"spawn({name!r}) returned "
                             f"{type(eng).__name__}, not a "
                             "ServingEngine")
        tr = _ReplicaTracer(self._tracer, name) \
            if self._tracer is not None else None
        if self._cost_ledger is not None:
            # every replica books on the ONE shared ledger (accounts
            # are rid-keyed, so a handed-off request keeps its single
            # open account across replicas); injected before session
            # creation so the session clock is ledger-armed from birth
            eng._ledger = self._cost_ledger
        role = self._roles.get(name, "both")
        mon = None
        if self._slo_rules is not None:
            mon = obs_slo.SLOMonitor(self._slo_rules, source=name,
                                     t0=t, log=self.slo_log,
                                     flight=self.flight,
                                     on_incident=self._slo_cbs)
        sess = eng.session(tracer=tr, replica=name,
                           expect_churn=self._expect_churn, role=role,
                           slo=mon)
        sess.clock.advance_to(t)   # a joiner starts life at NOW
        rep = _Replica(name, self._next_index, sess, joined_at=t,
                       role=role)
        rep.monitor = mon
        self._next_index += 1
        self.replicas.append(rep)
        self._hours[name] = {"joined": round(t, 6), "left": None,
                             "hours": 0.0}
        if self._autoscaler is not None and sess.sched is not None \
                and hasattr(sess.sched, "note_incident"):
            # a joiner enters mid-incident degraded like its peers:
            # page incidents that are still open reach its scheduler
            # now, not at the next incident (custom schedulers
            # without the seam are skipped, same as at incident-open)
            for inc in self._autoscaler.open_page_incidents():
                sess.sched.note_incident(inc)
        self._g_load("cluster_replica_load",
                     "queued + in-flight requests on a replica",
                     replica=name).set(0.0)
        if role != "both" and self._tracer is not None:
            self._tracer.instant("role", t=t, track="cluster",
                                 replica=name, role=role)
        return rep

    def _rep(self, name: str) -> _Replica:
        rep = self._find(name)
        if rep is None:
            raise ValueError(f"no live replica {name!r}")
        return rep

    def _join(self, name: str, t: float):
        self._add_replica(name, t)
        self.events_log.append({"t": round(t, 6), "event": "join",
                                "replica": name})
        if self._tracer is not None:
            self._tracer.instant("join", t=t, track="cluster",
                                 replica=name)

    def _drain(self, name: str, t: float):
        rep = self._rep(name)
        if rep.session.crashed:
            # the operator drained a replica that is already dead but
            # not yet detected: a graceful drain is impossible (the
            # in-flight rows died at the crash) — resolve as an
            # immediate failover so the crash salvage is NOT dropped
            self.events_log.append({"t": round(t, 6),
                                    "event": "drain_found_dead",
                                    "replica": name})
            self._declare_dead(rep, t)
            return
        if not rep.admitting:
            raise ValueError(f"replica {name!r} is already draining")
        rep.admitting = False
        rep.drained_at = t
        rep.session.more_expected = False
        pulled = rep.session.pull_unadmitted()
        self.events_log.append({"t": round(t, 6), "event": "drain",
                                "replica": name,
                                "requeued": [r.rid for r in pulled],
                                "in_flight": len(rep.session.active)})
        if self._tracer is not None:
            self._tracer.instant("drain", t=t, track="cluster",
                                 replica=name, requeued=len(pulled))
        for r in pulled:
            self.ledger[r.rid]["requeues"] += 1
            # a drained queue may hold a resumed (salvage-grown)
            # request in a heterogeneous cluster: route it through the
            # same fit-aware placement the retry path uses, so it can
            # never be submitted to a replica it cannot fit
            self._place_or_fail(r, t)
        self._maybe_retire(rep)

    def _maybe_retire(self, rep: _Replica):
        """A draining replica whose in-flight rows have all finished
        leaves the cluster; its pool census must balance with ZERO
        resident pages (every sequence freed) at removal. A replica
        that CRASHED while draining is never retired here — its crash
        salvage must leave through ``_declare_dead``'s failover, not
        be banked away with the corpse. A prefill-role replica with
        uncollected handoffs is not done either: banking it away
        would bury exported KV the router still owes a decode
        worker."""
        if rep.admitting or rep.session.in_flight() \
                or rep.session.queued() or rep.session.handoff_ready:
            return
        if rep.session.crashed:
            return
        self._bank_removal(rep, rep.session.clock.now())

    def _bank_removal(self, rep: _Replica, t: float, **extra) -> bool:
        """The one replica-removal block (drain retirement AND crash
        failover share it): finish the session, check the at-removal
        pool census (zero resident pages), bank the result, drop the
        replica and zero its load gauge, log the ``remove`` event
        (``extra`` tags crash removals with ``crashed``/``pool_epoch``)."""
        res = rep.session.finish()
        self._fold_handoff_stats(rep.session)
        if rep.monitor is not None:
            # membership change: the departing replica's monitor
            # retires — open incidents close (crash ones were already
            # resolved "failover" by _declare_dead) and its silence
            # stops being evaluated
            rep.monitor.retire(t, resolution="failover"
                               if extra.get("crashed")
                               else "replica_removed")
        cs = res.cache_stats
        ok = bool(cs.get("invariant_ok")
                  and cs.get("resident_pages") == 0)
        self.results[rep.name] = res
        self._close_hours(rep.name, t)
        self.replicas.remove(rep)
        self._g_load("cluster_replica_load",
                     "queued + in-flight requests on a replica",
                     replica=rep.name).set(0.0)
        self.events_log.append({
            "t": round(t, 6), "event": "remove",
            "replica": rep.name, "census_ok": ok,
            "resident_pages": cs.get("resident_pages"), **extra})
        if self._tracer is not None:
            attrs = {"crashed": True} if extra.get("crashed") else {}
            self._tracer.instant("remove", t=t, track="cluster",
                                 replica=rep.name, census_ok=ok,
                                 **attrs)
        return ok

    # --- placement --------------------------------------------------------
    def _place(self, r: Request, requeue: bool = False, only=None):
        """``only`` (predicate over replicas) narrows the candidate
        set — the retry path restricts a resumed request to survivors
        whose engine footprint actually admits it."""
        cands = [rep for rep in self.replicas if rep.admitting]
        if only is not None:
            cands = [rep for rep in cands if only(rep)]
        if not cands:
            raise RuntimeError(
                f"no admitting replica for {r.rid} — drained the whole "
                "cluster with work still arriving")
        rep = self.placement.place(r, cands)
        rep.session.submit(r)
        led = self.ledger.get(r.rid)
        if led is None:
            self.ledger[r.rid] = {"tenant": r.tenant,
                                  "replica": rep.name, "requeues": 0,
                                  "retries": 0, "path": [rep.name]}
        else:
            led["replica"] = rep.name
            led["path"].append(rep.name)
        # refresh EVERY admitting replica's gauge, not just the chosen
        # one — a replica that drains its backlog between placements
        # must not export its stale last-placement load
        for rep2 in cands:
            self._g_load("cluster_replica_load",
                         "queued + in-flight requests on a replica",
                         replica=rep2.name).set(
                float(rep2.session.load()))

    # --- KV handoff routing (the disaggregated decode stage) --------------
    def _fold_handoff_stats(self, sess: EngineSession):
        """Accumulate a session's import/reclaim counts into the
        router's handoff ledger exactly once — at removal (crash or
        retirement) or at the end-of-run bank."""
        self._handoff["imported"] += sess.handoff_stats["imported"]
        self._handoff["reclaimed"] += sess.handoff_stats["reclaimed"]
        sess.handoff_stats = {"imported": 0, "reclaimed": 0}
        for axis, n in sess.handoff_resharded.items():
            self._resharded[axis] = self._resharded.get(axis, 0) + n
        sess.handoff_resharded = {}

    def _collect_handoffs(self):
        """Drain every session's handoff bank and place each exported
        KV chain on a decode worker: delivery is priced at
        ``kv_transfer_unit`` per page on the shared timeline
        (``t_arrive = t_ready + pages * unit``), the ledger moves the
        request to its decode replica (counted once — the source
        forgot it at export), and a timeline tick lands at the
        delivery time so lanes advance to meet it. Candidates are no
        longer FILTERED on tp degree / page geometry / codec — each
        admitting, footprint-fitting replica is SCORED by the priced
        cost of the reshard/repage/transcode steps its import would
        run (``handoff_steps`` verdict + ``handoff_price``), and the
        placement policy breaks ties among prices; a twin prices 0.0
        so homogeneous fleets place identically to the old filters.
        Only a genuinely untransformable pairing (quantized source
        under a different codec, pressure across page geometries) or
        a footprint miss drops a candidate. An UNSTAMPED handoff
        (page_size/tp never filled in by the exporter) refuses loudly
        — scoring garbage geometry would mis-price every candidate. A
        handoff no admitting decode-capable replica can take is
        recorded FAILED — accounted, never silently dropped."""
        for rep in list(self.replicas):
            if not rep.session.handoff_ready:
                continue
            ready = rep.session.handoff_ready
            rep.session.handoff_ready = []
            for h in ready:
                self._handoff["exported"] += 1
                rid = h.req.rid
                led = self.ledger[rid]
                led["handoffs"] = led.get("handoffs", 0) + 1
                if h.page_size <= 0 or h.tp <= 0:
                    raise UnstampedHandoffError(h)
                cands, prices, axes = [], {}, {}
                for x in self.replicas:
                    if not (x.admitting and self._rep_fits(
                            x, len(h.req.prompt),
                            h.req.max_new_tokens)):
                        continue
                    steps = x.session.eng.handoff_steps(h)
                    if steps is None:
                        continue
                    cands.append(x)
                    prices[x.name] = x.session.eng.handoff_price(
                        h, steps)
                    axes[x.name] = steps
                pd = getattr(self.placement, "place_decode", None)
                if pd is None:
                    dest = _place_decode(h, cands, prices)
                else:
                    try:
                        dest = pd(h, cands, prices)
                    except TypeError:
                        # a pre-hetero custom policy takes (h, cands)
                        dest = pd(h, cands)
                if dest is None:
                    self._handoff["failed"] += 1
                    self.failed[rid] = (
                        "no admitting decode-capable replica can "
                        "adopt the handed-off KV chain (every "
                        "candidate is full, untransformable from "
                        "the chain's codec, or too small for its "
                        "footprint)")
                    self.events_log.append(
                        {"t": round(h.t_ready, 6),
                         "event": "handoff_failed", "rid": rid})
                    if self._tracer is not None:
                        self._tracer.instant("handoff_failed",
                                             t=h.t_ready,
                                             track="cluster", rid=rid)
                    if self._mon_cluster is not None:
                        self._mon_cluster.event(
                            "handoff_failed", h.t_ready,
                            severity=FAULT_SEVERITY["handoff_failed"],
                            close_t=h.t_ready, rids=[rid],
                            evidence={"pages": h.n_pages,
                                      "from": h.replica_from})
                    continue
                h.t_arrive = h.t_ready \
                    + self.kv_transfer_unit * h.n_pages
                if self._cost_ledger is not None:
                    # the transfer is router-priced (no engine clock
                    # ever times it), so it books on the router's own
                    # "cluster" book — elapsed grows by the same
                    # charge, keeping that book's conservation exact
                    self._cost_ledger.charge(
                        "cluster", "kv_transfer",
                        self.kv_transfer_unit * h.n_pages, rid=rid)
                dest.session.submit_handoff(h)
                led["replica"] = dest.name
                led["path"].append(dest.name)
                ev = {"t": round(h.t_ready, 6), "event": "handoff",
                      "rid": rid, "from": h.replica_from,
                      "to": dest.name, "pages": h.n_pages,
                      "arrive": round(h.t_arrive, 6)}
                # transform/price keys appear ONLY when the chosen
                # destination will actually run steps — twin-fleet
                # event streams stay byte-identical to pre-hetero
                steps = axes.get(dest.name) or ()
                if steps:
                    ev["transform"] = list(steps)
                    ev["price"] = round(prices[dest.name], 6)
                self.events_log.append(ev)
                if self._tracer is not None:
                    extra = ({"transform": ",".join(steps),
                              "price": round(prices[dest.name], 6)}
                             if steps else {})
                    self._tracer.instant(
                        "handoff", t=h.t_ready, track="cluster",
                        rid=rid, pages=h.n_pages, to=dest.name,
                        **{"from": h.replica_from}, **extra)
                self._push(h.t_arrive, 4, ("ht",))
                self._g_load("cluster_replica_load",
                             "queued + in-flight requests on a "
                             "replica", replica=dest.name).set(
                    float(dest.session.load()))

    # --- failure detection + failover -------------------------------------
    def _push(self, t: float, pri: int, item):
        heapq.heappush(self._heap, (float(t), pri, self._seq, item))
        self._seq += 1

    def _find(self, name: str) -> Optional[_Replica]:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        return None

    def _fault(self, ev: FaultEvent, t: float):
        rep = self._find(ev.replica)
        if rep is None:
            if ev.replica in self.results:
                # the target retired/died before the fault landed — a
                # seeded plan may legitimately outlive a replica; noop
                # loudly in the event log rather than crashing the
                # replay
                self.events_log.append({"t": round(t, 6),
                                        "event": f"{ev.kind}_noop",
                                        "replica": ev.replica,
                                        "reason": "already removed"})
                return
            # never joined (or joins later): the plan is unsatisfiable
            # as scheduled — silently swallowing the fault would make
            # the chaos evidence claim an injection that never
            # happened, so refuse loudly
            raise ValueError(
                f"fault plan targets replica {ev.replica!r} at "
                f"t={ev.t}, which has not joined the cluster — "
                "schedule faults inside the target's lifetime")
        if ev.kind == "crash":
            sess = rep.session
            n_inflight = len(sess.active)
            sess.crash()
            self.events_log.append({
                "t": round(t, 6), "event": "crash",
                "replica": rep.name, "in_flight": n_inflight,
                "queued": sess.queued()})
            if self._tracer is not None:
                self._tracer.instant("crash", t=t, track="cluster",
                                     replica=rep.name,
                                     in_flight=n_inflight)
            if rep.monitor is not None:
                # auto-open: the replica process died — ONE incident
                # per crash, open until the failover resolves it
                rep.monitor.event(
                    "crash", t, severity=FAULT_SEVERITY["crash"],
                    evidence={"in_flight": n_inflight,
                              "queued": sess.queued()})
        elif ev.kind == "stall":
            if rep.session.crashed:
                return
            # overlapping stalls extend, never shorten: the replica is
            # paused until the LATEST scheduled resume time
            rep.session.stall_until = max(
                rep.session.stall_until or 0.0, t + float(ev.duration))
            self.events_log.append({
                "t": round(t, 6), "event": "stall",
                "replica": rep.name, "duration": ev.duration})
            if self._tracer is not None:
                self._tracer.instant("stall", t=t, track="cluster",
                                     replica=rep.name,
                                     duration=ev.duration)
            if rep.monitor is not None:
                # one incident per injected stall, self-closing when
                # the pause ends (slow, not dead — "warn")
                rep.monitor.event(
                    "stall", t, severity=FAULT_SEVERITY["stall"],
                    close_t=t + float(ev.duration),
                    evidence={"duration": ev.duration,
                              "resume_at": round(
                                  rep.session.stall_until, 6)})
        else:  # decode_error
            sess = rep.session
            if sess.crashed or not sess.active:
                self.events_log.append({"t": round(t, 6),
                                        "event": "decode_error_noop",
                                        "replica": rep.name})
                return
            # deterministic victim: the OLDEST in-flight row (admit
            # time, rid tie-break) — a seeded plan needs no rid names
            rid = min(sess.active,
                      key=lambda r: (sess.active[r].t0, r))
            req, out = sess.abort_row(rid, reason="decode_error")
            self.events_log.append({
                "t": round(t, 6), "event": "decode_error",
                "replica": rep.name, "rid": rid, "salvaged": len(out)})
            if self._tracer is not None:
                self._tracer.instant("decode_error", t=t,
                                     track="cluster", replica=rep.name,
                                     rid=rid)
            if rep.monitor is not None:
                # a point incident: one slot failed, the row fails
                # over — service continues
                rep.monitor.event(
                    "decode_error", t,
                    severity=FAULT_SEVERITY["decode_error"],
                    close_t=t, rids=[rid],
                    evidence={"salvaged_tokens": len(out)})
            self._schedule_retry(req, out, t, reason="decode_error")

    def _collect_aborted(self, t: float) -> bool:
        """Drain every session's ``aborted`` bank (rows a DecodeError
        raised from inside a decode turn tore down — the backend-
        exception path, as opposed to the plan's decode_error events
        which abort through the router directly) and fail them over.
        Without a failover config there is no retry policy to apply,
        so losing the row silently is forbidden: raise instead."""
        got = False
        for rep in list(self.replicas):
            if not rep.session.aborted:
                continue
            aborted, rep.session.aborted = rep.session.aborted, []
            for req, out in aborted:
                got = True
                if self.failover is None:
                    raise RuntimeError(
                        f"{rep.name}: row {req.rid!r} aborted by a "
                        "decode fault but the router has no failover "
                        "config — pass failover=FailoverConfig() (or "
                        "a fault plan) so aborted work can be "
                        "re-placed instead of lost")
                if rep.monitor is not None:
                    # backend-raised DecodeError (no scheduled fault
                    # behind it): just as incident-worthy as a
                    # planned one
                    rep.monitor.event(
                        "decode_error", t,
                        severity=FAULT_SEVERITY["decode_error"],
                        close_t=t, rids=[req.rid],
                        evidence={"salvaged_tokens": len(out),
                                  "backend_raised": True})
                self._schedule_retry(req, out, t,
                                     reason="decode_error")
        return got

    def _probe(self, t: float):
        """One health-probe pass: live sessions answer (stalled ones
        included — slow is not dead), crashed ones stay silent; any
        replica silent past the heartbeat timeout is declared dead and
        failed over. Runs at every timeline step plus the standing
        probe ticks, so detection latency is bounded by
        ``timeout + interval`` even in an arrival gap."""
        cfg = self.failover
        for rep in list(self.replicas):
            if not rep.session.crashed:
                rep.last_seen = max(rep.last_seen, t)
            elif t - rep.last_seen >= cfg.heartbeat_timeout - 1e-9:
                self._declare_dead(rep, t)

    def _declare_dead(self, rep: _Replica, t: float):
        """Failover: the dead replica leaves the cluster NOW. Its
        queued-but-never-admitted backlog and its crash-salvaged
        in-flight rows are re-placed on survivors (with backoff and a
        retry budget); every moved request carries its metrics record
        and trace root with it, so the cluster counts it exactly once.
        The corpse's result banks only pre-crash completions, and its
        purged pool must census to zero resident pages at removal."""
        cfg = self.failover
        sess = rep.session
        silence = t - rep.last_seen
        missed = max(1, int(silence / cfg.heartbeat_interval))
        self._ctr_failovers.inc()
        queued = sess.pull_unadmitted(outcome="failover")
        salvage = sess.crash_salvage
        self.events_log.append({
            "t": round(t, 6), "event": "dead", "replica": rep.name,
            "silent_for": round(silence, 6),
            "missed_heartbeats": missed,
            "requeued": [r.rid for r in queued],
            "in_flight_lost": [r.rid for r, _ in salvage]})
        if self._tracer is not None:
            self._tracer.instant("dead", t=t, track="cluster",
                                 replica=rep.name,
                                 missed_heartbeats=missed,
                                 requeued=len(queued),
                                 in_flight_lost=len(salvage))
        if rep.monitor is not None:
            # the detector's conclusion: silence exceeded the timeout,
            # work is moving — pages; the crash incident it resolves
            # closes with resolution "failover"
            rep.monitor.event(
                "failover", t, severity=FAULT_SEVERITY["failover"],
                close_t=t,
                evidence={"silent_for": round(silence, 6),
                          "missed_heartbeats": missed,
                          "requeued": len(queued),
                          "in_flight_lost": len(salvage)},
                rids=[r.rid for r, _ in salvage])
            rep.monitor.close_kind("crash", t, resolution="failover")
        self._bank_removal(rep, t, crashed=True,
                           pool_epoch=sess.book.epoch)
        # queued work first (it never ran — plain re-place), then the
        # in-flight rows in admit order with their salvage
        for r in queued:
            self._schedule_retry(r, [], t, reason="failover_queued")
        for r, out in salvage:
            self._schedule_retry(r, out, t, reason="failover_inflight")

    def _place_or_fail(self, r: Request, t: float) -> bool:
        """Placement with the footprint guard for every re-placement
        path (drain requeues and failover retries): with the failover
        machinery active, candidates are filtered to replicas whose
        engine admits the request, and a request NO admitting replica
        can fit is recorded FAILED — accounted exactly once — instead
        of crashing the replay inside ``submit``'s validation. Without
        a failover config this is exactly ``_place`` (the PR-6 drain
        path, byte-identical)."""
        if self.failover is None:
            self._place(r, requeue=True)
            return True
        if not self._retry_fits(len(r.prompt), r.max_new_tokens):
            self.failed[r.rid] = (
                "no admitting replica can fit the request (none "
                "left, or its footprint exceeds every survivor's "
                "max_len)")
            self._ctr_retry("unplaceable")
            self.events_log.append({"t": round(t, 6),
                                    "event": "retry_unplaceable",
                                    "rid": r.rid})
            if self._tracer is not None:
                self._tracer.instant("retry_exhausted", t=t,
                                     track="cluster", rid=r.rid,
                                     reason="unplaceable")
            if self._mon_cluster is not None:
                self._mon_cluster.event(
                    "retry_exhausted", t,
                    severity=FAULT_SEVERITY["retry_exhausted"],
                    close_t=t, rids=[r.rid],
                    evidence={"reason": "unplaceable"})
            return False
        self._place(r, requeue=True,
                    only=lambda rep: self._rep_fits(
                        rep, len(r.prompt), r.max_new_tokens))
        return True

    # --- elastic autoscaling (the detect -> act loop) ----------------------
    def _close_hours(self, name: str, t: float):
        h = self._hours.get(name)
        if h is not None and h["left"] is None:
            h["left"] = round(t, 6)
            h["hours"] = round(max(0.0, h["left"] - h["joined"]), 6)

    def _standby_name(self, base: str) -> str:
        """The generation-suffix allocator: a standby base name that
        already served (and retired) this run rejoins as ``base#2``,
        ``base#3``, ... — the recycled replica gets a fresh
        ServeResult slot, so the exactly-once census (which is keyed
        by REQUEST, not replica) conserves and no retired history is
        overwritten. Direct (event-scheduled) joins of a retired name
        still refuse — only the autoscaler recycles."""
        if self._find(base) is None and base not in self.results:
            return base
        g = 2
        while self._find(f"{base}#{g}") is not None \
                or f"{base}#{g}" in self.results:
            g += 1
        return f"{base}#{g}"

    def _autoscale_on_incident(self, inc):
        """The autoscaler's incident subscription (rides the same
        ``on_incident`` list as any other subscriber): scale-worthy
        incidents arm the next tick's join; page-severity incidents
        flip QoS degradation tiers in EVERY live scheduler the moment
        they open — before any shed the overload would otherwise
        force — via the ``note_incident`` seam declared in PR 3."""
        if self._autoscaler.note_incident(inc) != "degrade":
            return
        n = 0
        for rep in self.replicas:
            sch = rep.session.sched
            if sch is not None and hasattr(sch, "note_incident"):
                sch.note_incident(inc)
                n += 1
        if n:
            self._autoscaler.log_degrade(inc)
            self.events_log.append({"t": round(inc.t_open, 6),
                                    "event": "autoscale",
                                    "action": "degrade",
                                    "incident": inc.id,
                                    "schedulers": n})
            if self._tracer is not None:
                self._tracer.instant("autoscale", t=inc.t_open,
                                     track="cluster", action="degrade",
                                     incident=inc.id)

    def _autoscale_tick(self, t: float):
        """One control-plane evaluation on the shared timeline: the
        autoscaler decides (cooldowns/hysteresis inside), the router
        executes — joins spawn through the standard ``_join`` path,
        drains through ``_drain`` (requeue + retirement semantics
        unchanged), role flips retag the replica and its session (the
        per-turn export sink and the placement policy both read the
        CURRENT role, so in-flight work finishes under the old stage
        and new work enters under the new one)."""
        # cluster-wide cumulative sheds (live sessions + banked
        # results): the loss signal that carries an armed scale-up
        # episode past its single triggering incident
        sheds = sum(len(rep.session.shed_log) for rep in self.replicas) \
            + sum(len(res.shed) for res in self.results.values())
        acts = self._autoscaler.decide(t, self.replicas,
                                       self._standby_name,
                                       sheds_total=sheds)
        for act in acts:
            kind = act["action"]
            self.events_log.append(
                {"t": round(t, 6), "event": "autoscale",
                 **{k: v for k, v in act.items() if k != "t"}})
            if self._tracer is not None:
                self._tracer.instant(
                    "autoscale", t=t, track="cluster", action=kind,
                    replica=act.get("replica"),
                    reason=act.get("reason"))
            if kind == "join":
                self._join(act["replica"], t)
            elif kind == "drain":
                self._drain(act["replica"], t)
            elif kind == "role":
                rep = self._rep(act["replica"])
                rep.role = act["to"]
                rep.session.role = act["to"]
                self._roles[act["replica"]] = act["to"]
                if self._tracer is not None:
                    self._tracer.instant("role", t=t, track="cluster",
                                         replica=rep.name,
                                         role=act["to"])
            # "drain_noop_crashed" and "degrade" execute nothing here:
            # the noop IS the action (logged loudly, the failover owns
            # the removal), and degrades actuate at incident-open time

    @staticmethod
    def _ctr_retry(reason: str):
        obs_metrics.REGISTRY.counter(
            "cluster_retries_total",
            "request re-placements after failures",
            reason=reason).inc()

    def _schedule_retry(self, r: Request, salvage: List[int],
                        t: float, reason: str):
        led = self.ledger[r.rid]
        led["retries"] += 1
        attempt = led["retries"]
        cfg = self.failover
        if attempt > cfg.retry_budget:
            self.failed[r.rid] = (f"retry budget exhausted "
                                  f"({cfg.retry_budget}) after "
                                  f"{reason}")
            self._ctr_retry("exhausted")
            self.events_log.append({
                "t": round(t, 6), "event": "retry_exhausted",
                "rid": r.rid, "attempts": attempt - 1})
            if self._tracer is not None:
                self._tracer.instant("retry_exhausted", t=t,
                                     track="cluster", rid=r.rid)
            if self._mon_cluster is not None:
                self._mon_cluster.event(
                    "retry_exhausted", t,
                    severity=FAULT_SEVERITY["retry_exhausted"],
                    close_t=t, rids=[r.rid],
                    evidence={"attempts": attempt - 1,
                              "after": reason})
            return
        self._ctr_retry(reason)
        delay = cfg.backoff(attempt)
        if self._tracer is not None:
            self._tracer.instant("retry", t=t, track="cluster",
                                 rid=r.rid, attempt=attempt,
                                 reason=reason, backoff=round(delay, 6),
                                 salvaged=len(salvage))
        # the resumed request is BUILT at pop time, not here: the
        # backoff window may see membership change (a joiner with a
        # smaller max_len, another crash), and the salvage trim must
        # size against the replicas that can actually receive it
        self._push(t + delay, 5, ("retry", r, salvage))

    def _resume_request(self, r: Request, salvage: List[int]):
        """Resume-from-prefix: the retried request re-enters with its
        already-emitted tokens appended to the prompt, so the survivor
        re-prefills (cheaply, where the prefix cache holds the shared
        prompt) instead of re-decoding, and the completed stream —
        salvage + what the retry emits — is token-identical to an
        uninterrupted run (prefill and decode agree on greedy
        argmax/hash; the sim backend is built resume-consistent for
        exactly this). Budgets shrink by what was already delivered:
        ``max_new_tokens`` and any ``cancel_after`` both count TOTAL
        stream tokens. Salvage is trimmed (newest tokens re-decoded
        instead) only if appending it would overflow every fitting
        survivor's max_len footprint. Returns ``(resumed_request,
        kept_salvage)`` — the caller banks ``kept_salvage`` into
        ``self._salvage`` ONLY after placement succeeds, so a request
        that ends up unplaceable never reports as resumed."""
        if not salvage:
            return r, []
        keep = len(salvage)
        while keep > 0:
            budget = r.max_new_tokens - keep
            if budget >= 1 and self._retry_fits(
                    len(r.prompt) + keep, budget):
                break
            keep -= 1
        if keep <= 0:
            return r, []
        kept = list(salvage[:keep])
        cancel = r.cancel_after
        if cancel is not None:
            cancel = max(1, cancel - keep)
        return dataclasses.replace(
            r, prompt=tuple(r.prompt) + tuple(kept),
            max_new_tokens=r.max_new_tokens - keep,
            cancel_after=cancel), kept

    @staticmethod
    def _rep_fits(rep: _Replica, prompt_len: int, budget: int) -> bool:
        # the engine's own footprint rule — _validate applies exactly
        # this arithmetic at submit
        e = rep.session.eng
        return e._footprint_len(prompt_len, budget) <= e.max_len

    def _retry_fits(self, prompt_len: int, budget: int) -> bool:
        """True when SOME admitting replica's engine footprint admits
        a resumed request of this size (pad-to-chunk + budget + decode
        chunk <= max_len) — retry placement is filtered to the fitting
        survivors, so one small joiner in a heterogeneous cluster must
        not doom a request a capable replica could serve. With NO
        admitting replica left (the last survivor drained inside the
        backoff window), or every survivor too small, nothing fits:
        the caller records the request FAILED instead of crashing the
        replay in _place."""
        return any(self._rep_fits(rep, prompt_len, budget)
                   for rep in self.replicas if rep.admitting)

    # --- the replay -------------------------------------------------------
    def run(self, trace: List[Request], events=()) -> ClusterResult:
        if self._ran:
            raise RuntimeError("a ClusterRouter runs once — build a "
                               "fresh router per replay")
        self._ran = True
        self._expect_churn = any(r.cancel_after is not None
                                 for r in trace)
        spec = self._trace_spec
        if spec is not None and spec is not False:
            if isinstance(spec, obs_trace.Tracer):
                self._tracer = spec
                self._tracer.clear()
            else:
                self._tracer = obs_trace.Tracer()
        if self.flight is not None and self._tracer is not None:
            # the flight recorder rides the tracer's mirror sink: the
            # most recent spans stay in its bounded ring for bundles
            self.flight.attach(self._tracer)
        for ev in events:
            t, op, name = ev
            if op not in ("drain", "join"):
                raise ValueError(f"lifecycle event {op!r}: use 'drain' "
                                 "or 'join'")
            self._push(float(t), 0 if op == "join" else 1, (op, name))
        t_last = 0.0
        for r in sorted(trace, key=lambda r: (r.arrival, r.rid)):
            self._push(r.arrival, 2, r)
            t_last = max(t_last, r.arrival)
        if self._faults is not None:
            for fev in self._faults:
                self._push(fev.t, 3, fev)
                t_last = max(t_last, fev.t)
            # standing health-probe ticks bound detection latency even
            # across arrival gaps; they run past the last scheduled
            # event far enough for the slowest detection + backoff
            cfg = self.failover
            horizon = t_last + cfg.heartbeat_timeout \
                + 2 * cfg.heartbeat_interval \
                + cfg.backoff(cfg.retry_budget)
            k = 1
            while k * cfg.heartbeat_interval <= horizon:
                self._push(k * cfg.heartbeat_interval, 4, ("hb",))
                k += 1
        if self._autoscaler is not None:
            # standing control-plane ticks: decisions evaluate at a
            # fixed cadence on the shared timeline (priority AFTER
            # arrivals/faults/probes at the same instant, so a tick
            # reads the state those events left), which is what makes
            # the action log byte-deterministic across replays. Ticks
            # are scheduled statically up to the last arrival/fault;
            # past it the loop below CHAINS further ticks while any
            # live replica still owes work, so a spike at the end of
            # the span keeps the control plane awake through its
            # backlog drain (late joins answered, recovered capacity
            # drained) without charging replica-hours for ticks over
            # a fully idle fleet
            iv = self._autoscaler.cfg.interval
            k = 1
            while k * iv <= t_last:
                self._push(k * iv, 6, ("as",))
                k += 1

        prev_tr = obs_trace.active()
        if self._tracer is not None:
            obs_trace.activate(self._tracer)
        try:
            for i in range(self.n_replicas):
                self._add_replica(f"r{i}", 0.0)
            has_roles = any(v != "both" for v in self._roles.values())
            t = 0.0
            while self._heap:
                t, _, _, item = heapq.heappop(self._heap)
                for rep in list(self.replicas):
                    rep.session.advance_until(t)
                    if not rep.admitting:
                        self._maybe_retire(rep)
                if self._slo_rules is not None:
                    # liveness feed, BEFORE any rule evaluation at t:
                    # a live session (stalled included — slow is not
                    # dead) answers the probe, so its monitor's
                    # silence reads zero across arrival gaps; a
                    # crashed session stays silent and only its clock
                    # advances — exactly what a heartbeat-silence
                    # rule is allowed to see
                    for rep in list(self.replicas):
                        if rep.monitor is None:
                            continue
                        if not rep.session.crashed:
                            rep.monitor.heartbeat(t)
                        else:
                            rep.monitor.advance(t)
                if has_roles:
                    # exports that completed during this advance move
                    # to decode workers before anything else acts on
                    # the new time
                    self._collect_handoffs()
                if self._faults is not None:
                    self._probe(t)
                if isinstance(item, FaultEvent):
                    self._fault(item, t)
                elif isinstance(item, Request):
                    self._place(item)
                elif item[0] == "retry":
                    r2, kept = self._resume_request(item[1], item[2])
                    if self._place_or_fail(r2, t) and kept:
                        self._salvage.setdefault(
                            r2.rid, []).extend(kept)
                elif item[0] == "as":
                    self._autoscale_tick(t)
                    iv = self._autoscaler.cfg.interval
                    if t + iv > t_last and any(
                            not rep.session.crashed
                            and rep.session.load() > 0
                            for rep in self.replicas):
                        # the tail extension: arrivals/faults are
                        # exhausted but some live replica still owes
                        # work, so the control plane stays awake one
                        # more tick (deterministic — chained off the
                        # same virtual state every replay sees).
                        # Crashed corpses are excluded: their frozen
                        # load never drains, and the heap must empty
                        # for the end-of-run failover rescue to fire.
                        self._push(t + iv, 6, ("as",))
                elif item[0] not in ("hb", "ht"):
                    op, name = item
                    if op == "drain" and self._faults is not None \
                            and self._find(name) is None:
                        # the drain's target was already removed by
                        # crash failover — a scheduled lifecycle event
                        # colliding with the fault plan noops loudly
                        # (same policy as _fault on a gone replica)
                        # instead of killing the replay
                        self.events_log.append(
                            {"t": round(t, 6), "event": "drain_noop",
                             "replica": name})
                    else:
                        (self._join if op == "join" else self._drain)(
                            name, t)
                self._collect_aborted(t)
                if not self._heap and self._faults is not None:
                    # a crash whose detection window outran the probe
                    # horizon (or whose failover pushed retries) must
                    # still be failed over before the run closes
                    for rep in list(self.replicas):
                        if rep.session.crashed:
                            self._declare_dead(
                                rep, max(t, rep.last_seen
                                         + self.failover
                                         .heartbeat_timeout))
            for rep in list(self.replicas):
                rep.session.more_expected = False
            if has_roles:
                # the disaggregation pipeline drains in stage order:
                # prefill-role lanes run dry first, their exports land
                # on decode workers, THEN everyone else finishes (a
                # decode worker finishing before its last handoffs
                # were submitted would bank an incomplete stream set)
                for rep in list(self.replicas):
                    if rep.session.role == "prefill":
                        rep.session.finish()
                self._collect_handoffs()
            for rep in list(self.replicas):
                self.results[rep.name] = rep.session.finish()
                self._fold_handoff_stats(rep.session)
                if rep.session.aborted:
                    # a decode fault fired inside the final backlog
                    # drain, after the last survivor-placement window
                    # closed — refusing loudly beats losing the row
                    raise RuntimeError(
                        f"{rep.name}: {len(rep.session.aborted)} "
                        "row(s) aborted after the replay closed — "
                        "nothing left to fail over to")
                if not rep.admitting:
                    # retire bookkeeping for replicas that were still
                    # streaming when the trace ran out
                    cs = self.results[rep.name].cache_stats
                    self.events_log.append({
                        "t": round(rep.session.clock.now(), 6),
                        "event": "remove", "replica": rep.name,
                        "census_ok": bool(
                            cs.get("invariant_ok")
                            and cs.get("resident_pages") == 0),
                        "resident_pages": cs.get("resident_pages")})
                self._close_hours(rep.name, rep.session.clock.now())
                self.replicas.remove(rep)
        finally:
            if self._tracer is not None:
                if prev_tr is not None:
                    obs_trace.activate(prev_tr)
                else:
                    obs_trace.deactivate()
        if self._tracer is not None and isinstance(spec, str):
            self._tracer.export(spec)
        ho = dict(self._handoff) if self._handoff["exported"] else {}
        if ho and self._resharded:
            # per-axis transform counts ride the handoff block only
            # when an import actually resharded — twin results carry
            # the same keys they always did
            ho["resharded"] = dict(self._resharded)
        return ClusterResult(placement=self.placement.name,
                             results=self.results, ledger=self.ledger,
                             events=self.events_log,
                             trace=self._tracer,
                             salvaged=self._salvage,
                             failed=self.failed,
                             faulted=(self._faults is not None
                                      or bool(self.failed)
                                      or any(led.get("retries")
                                             for led in
                                             self.ledger.values())),
                             handoffs=ho,
                             incidents=(list(self.slo_log.incidents)
                                        if self.slo_log is not None
                                        else None),
                             slo_log=self.slo_log,
                             flight=self.flight,
                             autoscale=(self._autoscaler.summary()
                                        if self._autoscaler is not None
                                        else None),
                             replica_hours=dict(self._hours),
                             cost_rollup=(
                                 self._cost_ledger.rollup()
                                 if self._cost_ledger is not None
                                 else None),
                             cost_ledger=self._cost_ledger)
