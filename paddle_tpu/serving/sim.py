"""Simulated paged decode factory: the scale harness for the serving
stack.

A real ``llama_serving_decode_factory`` prices a 10^5-request cluster
trace out of reach on CPU — every prefill and decode turn is a jitted
program call. The CLUSTER layer's claims, though, are about placement,
scheduling, drain/join bookkeeping and prefix-cache *routing*, none of
which need real logits: they need a decode backend whose tokens are a
deterministic function of the request's full token history **as read
back through the engine's own page tables**.

``SimServing`` is exactly that surface:

- the "KV pool" is one int array ``pools[page, offset]``; prefill
  writes the prompt's tokens through the page table (honoring the
  chunk-aligned ``resume_from`` prefix-cache skip — skipped positions
  must already hold the publisher's identical tokens), decode writes
  each input token at its position before emitting the next;
- there is ONE token rule: the next token after any history is a hash
  of the FULL pooled sequence, read back through the page table every
  step — so a wrong page table, a stale prefix chain, or a
  cross-replica pool mixup diverges the stream (the same failure
  surface the real backend has, at numpy speed);
- because prefill and decode apply the SAME rule to the same history,
  the sim is RESUME-CONSISTENT exactly like the real model: prefilling
  ``prompt + already_emitted`` yields the token a decode step would
  have emitted next. That is the property the fault-tolerance layer's
  resume-from-prefix retries stand on — a request failed over
  mid-decode re-enters with its emitted tokens as prompt and the
  completed stream must be token-identical to an uninterrupted run;
- tokens depend ONLY on the request's own history, so greedy parity
  across placement policies / replica counts / crash-failover retries
  / a single-engine oracle is the honest invariant it is with the
  real model.

``wants_numpy_`` tells the engine to skip the ``jnp.asarray`` staging
(pure overhead here). Paged-only by design: build engines with
``policy="paged"``; the dense parts raise if a wave is ever routed
there.
"""
from __future__ import annotations

import numpy as np

_MUL = np.uint64(6364136223846793005)   # splitmix/LCG-grade odd mult


# the dense-introspection stub is SHARED with the TP factory
# (models.nlp.llama_decode.PagedOnlyDense) so the engine's dense
# surface has exactly one stub to keep in lockstep
_SIM_DENSE_REASON = (
    "SimServing is paged-only (policy='paged'): the sim validates "
    "paged bookkeeping at scale; route dense waves to a real "
    "factory")


class SimServing:
    """Drop-in ``serving=`` object for ``ServingEngine`` (paged only).

    ``vocab`` bounds emitted tokens to ``[1, vocab)`` (0 is the pool's
    padding value and never emitted); ``salt`` decorrelates two sims
    that should NOT agree (a negative control for parity tests).
    """

    wants_numpy_ = True
    # KVHandoff canonical-layout descriptor: exported chains are
    # (n_pages, page_size) token rows, not head-major tensor leaves
    kv_layout_ = "tokens"

    def __init__(self, *, max_len: int = 64, page_size: int = 8,
                 n_pool_pages: int | None = None, slots: int = 8,
                 vocab: int = 509, salt: int = 0,
                 chunked_prefill: int | None = None, tp=None,
                 lora_slots: int | None = None,
                 spec_accept: float | None = None,
                 kv_quant: str | None = None,
                 grammar_slots: int | None = None,
                 grammar_states: int = 64):
        if max_len % page_size:
            raise ValueError(f"max_len {max_len} must be a multiple of "
                             f"page_size {page_size}")
        # ``tp`` (TPConfig / int degree): the sim's TENSOR-PARALLEL
        # stand-in. The token pool stays ONE host array — the token
        # rule hashes full histories, there are no heads to split —
        # but the factory advertises the tp degree (``tp_``) and the
        # per-device byte arithmetic (``pool_device_bytes``: total /
        # size, exactly what a head-sharded pool measures), so the
        # ENGINE/CLUSTER tp machinery — paged-policy coercion, pool
        # byte census + gauge, handoff tp tags and placement filters —
        # runs at 10^5-request scale. Compute-sharding parity is the
        # real factory's claim, not the sim's.
        from ..models.nlp.llama_decode import (GrammarConfig,
                                               LoRAConfig,
                                               PagedOnlyDense,
                                               as_tp_config)
        self.tp_ = as_tp_config(tp)
        # ``lora_slots``: the sim's MULTI-ADAPTER stand-in. A real
        # adapter is a low-rank weight delta; the sim's is a per-slot
        # SALT folded into the token rule, so two adapters diverge
        # every stream while slot 0 (salt 0, the reserved identity)
        # emits exactly the base rule — the same observable contract
        # the real bank has, at numpy speed. The factory advertises
        # ``lora_`` plus the ``init_adapter_bank``/``upload_adapter``
        # hooks the engine's AdapterCache consumes; a delta set here
        # is ``{"salt": int}`` (or a bare int).
        self.lora_ = None if lora_slots is None \
            else LoRAConfig(n_slots=int(lora_slots), rank=1)
        # ``grammar_slots``: the sim's CONSTRAINED-DECODING stand-in.
        # The real factory masks logits with a packed per-state
        # allow-bitmask before its argmax; the sim's token rule picks
        # ``allowed[hash % len(allowed)]`` from the SAME unpacked bank
        # row — deterministic, and an all-allow row (flat id 0, the
        # identity every free row indexes) special-cases to EXACTLY
        # the base rule, so free rows are byte-identical to a
        # grammar-less sim. The factory advertises ``grammar_`` /
        # ``grammar_vocab_`` plus the ``init_grammar_bank``/
        # ``upload_grammar`` hooks the engine's GrammarCache consumes.
        self.grammar_ = None if grammar_slots is None \
            else GrammarConfig(n_slots=int(grammar_slots),
                               max_states=int(grammar_states))
        self.grammar_vocab_ = int(vocab)
        # ``kv_quant``: the sim's QUANTIZED-PAGE-TIER stand-in. The
        # token pool is lossless content (int64 tokens have no numerics
        # to degrade — greedy parity with the unquantized sim is EXACT,
        # which is precisely what makes the engine/cluster bookkeeping
        # testable at 10^5 scale), but the factory advertises the mode
        # (``kv_quant_``), per-page prices (``page_bytes_``: a
        # synthetic fp row vs an int8+scale row) and a no-op
        # ``compact_pages``, so the ENGINE machinery — stored-bytes
        # census, pressure incidents, compaction batches, handoff tier
        # tags — runs for real. Accuracy claims live with the real
        # factory.
        if kv_quant not in (None, "int8", "pressure"):
            raise ValueError(f"kv_quant {kv_quant!r}: use None, "
                             "'int8' or 'pressure'")
        self.kv_quant_ = kv_quant
        self.page_bytes_ = None if kv_quant is None else \
            (page_size * 8, page_size * 4 + 4)
        # the host-arena tier's full-precision per-page price,
        # advertised UNCONDITIONALLY (the int64 token pool is 8
        # bytes/token whether or not a quant tier is armed) — the
        # engine's hostmem= arming reads it so arena budgets price
        # identically with and without kv_quant
        self.page_host_bytes_ = page_size * 8
        self.dense = PagedOnlyDense(_SIM_DENSE_REASON)
        if vocab < 3:
            raise ValueError("vocab must be >= 3")
        if n_pool_pages is None:
            n_pool_pages = slots * (max_len // page_size) + 1
        self.max_len_ = max_len
        self.page_size_ = page_size
        self.n_pool_pages_ = n_pool_pages
        self.chunked_prefill_ = chunked_prefill or page_size
        if self.chunked_prefill_ % page_size:
            raise ValueError("chunked_prefill must be a page multiple")
        self.vocab = int(vocab)
        self.salt = int(salt)
        # wrapping-uint64 polynomial-hash powers, highest degree first
        # (built in python ints mod 2^64 — numpy warns on uint64
        # SCALAR overflow even though the wrap is exactly what we want)
        mul, mask = int(_MUL), (1 << 64) - 1
        p, acc = [], 1
        for _ in range(max_len):
            p.append(acc)
            acc = (acc * mul) & mask
        self._pow = np.asarray(p, np.uint64)
        pools = np.zeros((n_pool_pages, page_size), np.int64)
        self.paged_parts = (None, None, pools, self._make_prefill(),
                            None, self._make_decode_n())
        # the fused ragged-prefill entry point (the engine's
        # ragged_prefill= flag probes for this attribute), mirroring
        # the real factory's contract: one call runs ONE pending chunk
        # per row at per-row offsets, returning per-row first tokens
        # that are meaningful only for rows whose final chunk this is
        self.prefill_ragged = self._make_prefill_ragged()
        # ``spec_accept``: the sim's SPECULATIVE stand-in. The real
        # spec factory's draft is a second model whose proposals the
        # target verifies; the sim's draft proposes the TRUE next
        # token with this probability (decided by a second
        # deterministic hash of the same history, so acceptance
        # replays bit-identically) and a guaranteed-different token
        # otherwise. Verification is the real acceptance arithmetic —
        # emitted tokens are always the true rule's, so greedy parity
        # with plain decode is exact, and only TIMING (rounds per
        # token) depends on the draft. The factory then advertises
        # ``spec_parts`` shaped like the real one's; the draft "pool"
        # is a zero-size array (the sim's truth pool is the token
        # history itself, so the draft reads the same pool — the
        # page-chain sharing the model-side claim is about).
        self.spec_accept = None
        self.spec_parts = None
        if spec_accept is not None:
            if not 0.0 <= float(spec_accept) <= 1.0:
                raise ValueError("spec_accept is an acceptance "
                                 "probability in [0, 1]")
            self.spec_accept = float(spec_accept)
            self.spec_parts = (None, None,
                               np.zeros((0,), np.int64),
                               self._make_spec_prefill(),
                               self._make_spec_step())

    # --- the token rule ---------------------------------------------------
    def _hash(self, seq, adapter_salt: int = 0) -> int:
        """The salted uint64 wraparound polynomial hash of ``seq`` —
        the one source of randomness both token rules draw from."""
        seq = np.asarray(seq, np.uint64)
        L = len(seq)
        with np.errstate(over="ignore"):
            h = (seq * self._pow[L - 1::-1]).sum()
        return (int(h) + self.salt + int(adapter_salt)) \
            & ((1 << 64) - 1)

    def _token(self, seq, adapter_salt: int = 0) -> int:
        """THE greedy rule: next token after history ``seq`` = uint64
        wraparound polynomial hash of the whole sequence (deterministic
        on any platform), mapped to [1, vocab). Prefill applies it to
        the pooled prompt; every decode step applies it to the pooled
        prompt + emitted-so-far — one rule, so prefill and decode are
        RESUME-CONSISTENT (see the module docstring). ``adapter_salt``
        (multi-adapter serving) folds the row's adapter into the hash:
        salt 0 — slot 0, the identity — is EXACTLY the base rule."""
        return 1 + self._hash(seq, adapter_salt) % (self.vocab - 1)

    def _token_masked(self, seq, adapter_salt: int, allow) -> int:
        """The CONSTRAINED rule: the same hash picks among the mask
        row's allowed tokens. An all-allow row (the reserved flat id
        0 every free row indexes) is EXACTLY the base rule — free
        rows in a constrained wave stay byte-identical to
        ``grammar=None``. Mirrors the real factory's masked argmax:
        deterministic in (history, mask)."""
        allow = np.asarray(allow, bool)
        if allow.all():
            return self._token(seq, adapter_salt)
        allowed = np.nonzero(allow)[0]
        if len(allowed) == 0:
            raise ValueError("grammar mask allows no token (dead "
                             "state reached — engine bug)")
        return int(allowed[self._hash(seq, adapter_salt)
                           % len(allowed)])

    def _grammar_row(self, grammar, s: int):
        """Unpack row ``s`` of a ``(bank, gids)`` grammar payload to
        a (vocab,) bool allow vector; None without a payload or for
        flat id 0 fast-path handled by the caller via all-allow."""
        from .grammar import unpack_row
        bank, gids = grammar
        gid = int(np.asarray(gids)[s])
        return unpack_row(np.asarray(bank)[gid], self.vocab)

    def _draft_token(self, seq) -> int:
        """The sim DRAFT's proposal after history ``seq``: the true
        next token with probability ``spec_accept`` (a second
        deterministic hash of the same history decides, so two seeded
        replays accept identically), otherwise a token guaranteed to
        differ — which the verify arithmetic then rejects."""
        t = self._token(seq)
        seq_a = np.asarray(seq, np.uint64)
        L = len(seq_a)
        with np.errstate(over="ignore"):
            h = (seq_a * self._pow[L - 1::-1]).sum()
        h = (int(h) * 0x9E3779B97F4A7C15 + 0xD1B54A32D192ED03) \
            & ((1 << 64) - 1)
        u = (h >> 11) / float(1 << 53)
        if u < self.spec_accept:
            return t
        return 1 + (t % (self.vocab - 1))  # != t for vocab >= 3

    def _make_spec_prefill(self):
        """The sim draft's prefill: a no-op returning the (empty)
        draft pool — the sim's token rule derives every proposal from
        the TRUE pool content, so there is nothing to warm (the real
        factory's draft prefill writes draft K/V through the shared
        page chain)."""
        def spec_prefill(outer, layers, toks, pt, lens, pools,
                         resume_from: int = 0, lora=None):
            return np.zeros((1,), np.int64), pools

        spec_prefill._cache_size = lambda: 0
        return spec_prefill

    def _make_spec_step(self):
        ps = self.page_size_

        def spec_step(outer_t, layers_t, outer_d, layers_d, prev,
                      toks, pt, lens, pools, pools_d, k):
            """One batched speculative round, the real acceptance
            arithmetic at numpy speed: per active row, draft ``k``
            proposals (each conditioned on the draft's OWN walk, like
            the real draft cache), verify against the true rule,
            advance by accepted prefix + correction. The accepted
            true tokens land in the pool through the page table —
            wrong tables/chains diverge streams exactly like plain
            decode."""
            toks = np.asarray(toks)
            pt = np.asarray(pt)
            lens = np.asarray(lens)
            S = toks.shape[0]
            counts = np.zeros((S,), np.int64)
            cands = np.zeros((S, k + 1), np.int64)
            for s in range(S):
                L = int(lens[s])
                if L <= 0:
                    continue  # plain/empty slot rides along
                # this round's input token lands at position L first
                # (the verify block's write), then the history reads
                # back THROUGH the table
                pools[pt[s, L // ps], L % ps] = int(toks[s])
                npages = -(-(L + 1) // ps)
                hist = [int(x) for x in
                        pools[pt[s, :npages]].reshape(-1)[:L + 1]]
                drafts, truths = [], []
                h = list(hist)
                for i in range(k):
                    truths.append(self._token(h))
                    drafts.append(self._draft_token(h))
                    h.append(drafts[-1])
                truths.append(self._token(h))  # the bonus token
                n = 0
                while n < k and drafts[n] == truths[n]:
                    n += 1
                emitted = drafts[:n] + [truths[n]]
                counts[s] = n
                cands[s, :n + 1] = emitted
                # accepted TRUE tokens persist at L+1..L+n; the
                # correction token is the row's next input, written
                # by the NEXT round/turn — the decode_n discipline
                for j in range(n):
                    p = L + 1 + j
                    pools[pt[s, p // ps], p % ps] = emitted[j]
            return counts, cands, pools, pools_d

        spec_step._cache_size = lambda: 0
        return spec_step

    # --- adapter-bank hooks (AdapterCache's device seam) ------------------
    def init_adapter_bank(self):
        if self.lora_ is None:
            raise ValueError("SimServing built without lora_slots")
        return np.zeros((self.lora_.n_slots,), np.int64)

    @staticmethod
    def upload_adapter(bank, slot, deltas):
        salt = deltas["salt"] if isinstance(deltas, dict) else deltas
        bank[int(slot)] = int(salt)
        return bank

    # --- grammar-bank hooks (GrammarCache's device seam) ------------------
    def init_grammar_bank(self):
        """The packed allow-bitmask bank, sim edition: the SAME layout
        the real factory stages on device — ``(n_slots * max_states,
        ceil(vocab/32))`` uint32, slot 0 (flat ids ``0..max_states-1``)
        all-ones so free rows index the reserved all-allow identity —
        just host numpy (``wants_numpy_``)."""
        if self.grammar_ is None:
            raise ValueError("SimServing built without grammar_slots")
        ns, ms = self.grammar_.n_slots, self.grammar_.max_states
        words = (self.vocab + 31) // 32
        bank = np.zeros((ns * ms, words), np.uint32)
        bank[:ms] = np.uint32(0xFFFFFFFF)
        return bank

    def upload_grammar(self, bank, slot, compiled):
        """Write a compiled automaton's per-state masks into its slot's
        block (zero-padding unused state rows — a stale mask from the
        evicted tenant must never leak into a shorter successor)."""
        ms = self.grammar_.max_states
        n = int(compiled.n_states)
        if n > ms:
            raise ValueError(f"automaton has {n} states but the bank "
                             f"holds max_states={ms}")
        lo = int(slot) * ms
        bank[lo:lo + ms] = 0
        bank[lo:lo + n] = np.asarray(compiled.masks, np.uint32)
        return bank

    # --- the factory callables --------------------------------------------
    def _make_prefill(self):
        ps = self.page_size_
        C = self.chunked_prefill_

        def prefill(outer, layers, toks, pt, lens, pools,
                    resume_from: int = 0, lora=None, grammar=None):
            toks = np.asarray(toks)
            pt = np.asarray(pt)
            L = int(np.asarray(lens)[0])
            T = toks.shape[1]
            # the real factory clamps resume so the FINAL chunk always
            # runs (the last-position logits must exist)
            resume = min(int(resume_from), T - C)
            resume = max(resume, 0)
            for pos in range(resume, L):
                pools[pt[0, pos // ps], pos % ps] = toks[0, pos]
            pages = pt[0, :-(-L // ps)]
            seq = pools[pages].reshape(-1)[:L]
            a_salt = 0
            if lora is not None:
                bank, ids = lora
                a_salt = int(np.asarray(bank)[int(np.asarray(ids)[0])])
            if grammar is not None:
                first = self._token_masked(
                    seq, a_salt, self._grammar_row(grammar, 0))
            else:
                first = self._token(seq, a_salt)
            return np.asarray([first], np.int64), pools

        prefill._cache_size = lambda: 0  # no jit cache to watch
        return prefill

    def _make_prefill_ragged(self):
        ps = self.page_size_

        def prefill_ragged(outer, layers, chunk, starts, pt, lens,
                           pools, lora=None, grammar=None):
            """The real factory's fused lane dispatch, sim edition:
            row r writes the C tokens of ``chunk[r]`` at absolute
            positions ``starts[r]..`` through its own page table, then
            rows whose length-1 position falls inside the window (the
            row's FINAL chunk) hash their full pooled history into the
            first token. Idle rows (the engine points them at page 0)
            write garbage there, the pool convention."""
            chunk = np.asarray(chunk)
            starts = np.asarray(starts)
            pt = np.asarray(pt)
            lens = np.asarray(lens)
            R, C = chunk.shape
            bank = ids = None
            if lora is not None:
                bank, ids = lora
                bank, ids = np.asarray(bank), np.asarray(ids)
            firsts = np.zeros((R,), np.int64)
            for s in range(R):
                L = int(lens[s])
                st = int(starts[s])
                for pos in range(st, min(st + C, L)):
                    pools[pt[s, pos // ps], pos % ps] = \
                        chunk[s, pos - st]
                if not (st <= L - 1 < st + C):
                    continue  # mid-prompt row: no logits to harvest
                pages = pt[s, :-(-L // ps)]
                seq = pools[pages].reshape(-1)[:L]
                a_salt = int(bank[int(ids[s])]) if bank is not None \
                    else 0
                if grammar is not None:
                    firsts[s] = self._token_masked(
                        seq, a_salt, self._grammar_row(grammar, s))
                else:
                    firsts[s] = self._token(seq, a_salt)
            return firsts, pools

        prefill_ragged._cache_size = lambda: 0
        return prefill_ragged

    def _make_decode_n(self):
        ps = self.page_size_

        def decode_n(outer, layers, toks, pt, lens, pools, n: int,
                     lora=None, grammar=None):
            toks = np.asarray(toks)
            pt = np.asarray(pt)
            lens = np.asarray(lens)
            S = toks.shape[0]
            bank = ids = None
            if lora is not None:
                bank, ids = lora
                bank, ids = np.asarray(bank), np.asarray(ids)
            emits = np.zeros((n, S), np.int64)
            for s in range(S):
                L = int(lens[s])
                if L <= 0:
                    continue  # empty slot rides along (page-0 row)
                a_salt = int(bank[int(ids[s])]) if bank is not None \
                    else 0
                # grammar ids are DISPATCH-TIME state (advanced
                # host-side), so every scanned step masks with the
                # same row — the engine clamps n=1 for constrained
                # waves, exactly like the real factory's decode_n
                g_allow = None if grammar is None \
                    else self._grammar_row(grammar, s)
                cur = int(toks[s])
                for k in range(n):
                    pools[pt[s, L // ps], L % ps] = cur
                    # read the FULL history back through the table —
                    # a wrong table/chain/pool diverges every token
                    npages = -(-(L + 1) // ps)
                    seq = pools[pt[s, :npages]].reshape(-1)[:L + 1]
                    if g_allow is not None:
                        cur = self._token_masked(seq, a_salt, g_allow)
                    else:
                        cur = self._token(seq, a_salt)
                    emits[k, s] = cur
                    L += 1
            return emits, None, pools

        decode_n._cache_size = lambda: 0
        return decode_n

    def pool_total_bytes(self, pools) -> int:
        """The pool's byte footprint as STORED: the sim's token pool
        is physically int64 whatever the codec, so under
        kv_quant='int8' the price is the advertised int8+scale row
        cost, not the host array's nbytes — the arithmetic the real
        int8 factory gets for free from its int8 leaves."""
        if self.kv_quant_ == "int8":
            return self.n_pool_pages_ * self.page_bytes_[1]
        return int(np.asarray(pools).nbytes)

    def pool_device_bytes(self, pools) -> int:
        """One device's share of the pool under the advertised tp
        degree (the engine's per-device byte census hook)."""
        size = self.tp_.size if self.tp_ is not None else 1
        return self.pool_total_bytes(pools) // size

    @staticmethod
    def compact_pages(pools, mask):
        """Pressure-tier compaction, sim edition: token content is
        lossless so the pool is untouched — the BOOKKEEPING (tier
        sets, stored-bytes census, compaction counters) is what the
        engine exercises here."""
        return pools

    # --- KV handoff data plane ---------------------------------------------
    @staticmethod
    def export_kv_pages(pools, ids):
        """Copy the pool rows of ``ids`` for a KV handoff (the sim's
        "KV" is the token content itself, so a handoff moves exactly
        what decode reads back through the page table — a wrong chain
        or a dropped page diverges the stream like the real model)."""
        return pools[np.asarray(ids, np.int64)].copy()

    @staticmethod
    def import_kv_pages(pools, ids, data):
        """Scatter exported page content into this pool at ``ids``
        (the importer's freshly allocated chain)."""
        pools[np.asarray(ids, np.int64)] = data
        return pools

    # --- heterogeneous-handoff transforms (reshard-on-import) --------------
    @staticmethod
    def reshard_kv_pages(data):
        """The sim's token pool is ONE host array whatever tp degree
        it advertises (there are no heads to split), so gathering the
        chain into the canonical layout is the identity — the PRICED
        step still runs, which is exactly what the 10^5-scale hetero
        bookkeeping needs."""
        return data

    @staticmethod
    def repage_kv_pages(data, page_size_from, page_size_to, n_tokens):
        """Refold an exported ``(n_pages, page_size_from)`` token
        chain to the destination geometry: tokens are packed in chain
        order, pad slots return to 0 (the pool padding value a direct
        prefill leaves in its last page's slack)."""
        n_to = -(-int(n_tokens) // int(page_size_to))
        flat = np.asarray(data).reshape(-1)[:n_tokens]
        out = np.zeros((n_to * int(page_size_to),), flat.dtype)
        out[:n_tokens] = flat
        return out.reshape(n_to, int(page_size_to))

    @staticmethod
    def transcode_kv_pages(data, quant_from, quant_to):
        """Codec transcode, sim edition: int64 token content is
        lossless under every codec, so the data is untouched — the
        BOOKKEEPING (priced span, tier mirror via ``quant_pages``,
        stored-bytes census) is what the engine exercises."""
        if quant_from is not None:
            raise ValueError(
                f"transcode: source codec {quant_from!r} is not "
                "transcodable (only full-precision chains re-encode)")
        return data

    # --- the offline oracle -----------------------------------------------
    def expected_stream(self, prompt, n_tokens: int,
                        adapter_salt: int = 0, grammar=None):
        """The token stream a request with ``prompt`` generates,
        computed WITHOUT any engine — the closed-form oracle parity
        tests compare engine outputs against. (The engine path reads
        these same values back through page tables; this path replays
        the recurrence directly.) Resume identity falls out of the one
        token rule: ``expected_stream(prompt + s[:e], n-e)`` equals
        ``expected_stream(prompt, n)[e:]`` for any emitted prefix
        ``s = expected_stream(prompt, n)``. ``adapter_salt`` is the
        request's adapter (0 = base model). ``grammar`` — a
        ``CompiledGrammar`` — walks the automaton exactly like the
        engine: each emission is the constrained rule under the
        current state's mask, the state advances on the emitted
        token, and the stream STOPS at an accepting state (shorter
        than ``n_tokens`` when the automaton accepts first)."""
        from .grammar import unpack_row
        hist = [int(t) for t in prompt]
        out = []
        state = None if grammar is None else grammar.start
        for _ in range(max(0, n_tokens)):
            if grammar is None:
                nxt = self._token(hist, adapter_salt)
            else:
                allow = unpack_row(grammar.masks[state], self.vocab)
                nxt = self._token_masked(hist, adapter_salt, allow)
                state = grammar.step(state, nxt)
            out.append(nxt)
            hist.append(nxt)
            if grammar is not None and grammar.accepts_at(state):
                break
        return out


def make_sim_serving(**kw) -> SimServing:
    """Convenience constructor mirroring the real factory's signature
    style: ``make_sim_serving(max_len=64, page_size=8, slots=8, ...)``."""
    return SimServing(**kw)
