"""Trace-driven serving workloads: seeded, replayable request streams.

The serving engine is only as honest as its load. A static-batch
microbench answers "how fast is one shape"; a server answers "how fast
is a STREAM" — requests arriving over time (Poisson singles, bursts),
ragged prompt/output lengths, shared system prompts, and mid-run churn
(clients disconnecting). ``synthesize_trace`` generates exactly that
mix from one seed, so the same workload replays bit-identically across
policies, runs, and machines; ``save_trace``/``load_trace`` round-trip
it as JSONL for pinned regression traces.
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Request:
    """One serving request as the trace records it.

    ``arrival`` is in the engine clock's units (seconds for a measured
    replay; abstract units under a fixed-cost clock). ``prefix_group``
    marks shared-system-prompt cohorts: every request in a group opens
    with the same token prefix, the prefix-cache case.
    ``cancel_after`` models churn — the client disconnects after that
    many generated tokens and the engine must evict mid-stream.

    QoS fields (read by ``scheduler.QoSScheduler``; the default FIFO
    engine ignores them, so PR-2 traces replay unchanged):
    ``tenant`` names the traffic source for weighted fair queueing;
    ``priority`` is a strict class (higher preempts lower at admission,
    never mid-flight); ``deadline_ms`` is the end-to-end SLO relative
    to arrival, in milliseconds of clock time (1 clock unit = 1000 ms,
    so a fixed-cost replay can reason about deadlines too).

    ``adapter`` names the LoRA adapter this request decodes with
    (multi-model serving; ``None`` — the default, and what every
    legacy trace loads as — is the base model, whose replay is
    byte-identical to pre-adapter engines). The JSONL record carries
    the key only when set, so adapter-less traces round-trip
    byte-identically.

    ``session``/``turn`` mark multi-turn conversation membership
    (``synthesize_session_trace``): every turn of a session carries
    the session id and its 1-based turn index, and each turn's prompt
    EXTENDS the previous turn's — the shape whose round-2 prefixes
    the KV memory hierarchy serves from swapped-in pages. Both
    default None (one-shot requests, every legacy trace), and the
    JSONL record carries the keys only when set — the
    ``Request.adapter`` convention, so session-less traces round-trip
    byte-identically.

    ``schema`` names the grammar/JSON-schema this request's output
    must satisfy (constrained decoding; ``synthesize_schema_trace``).
    ``None`` — the default, and what every legacy trace loads as —
    is a free-running stream. The JSONL record carries the key only
    when set, so schema-less traces round-trip byte-identically.
    """

    rid: str
    arrival: float
    prompt: Tuple[int, ...]
    max_new_tokens: int
    prefix_group: Optional[int] = None
    cancel_after: Optional[int] = None
    tenant: Optional[str] = None
    priority: int = 0
    deadline_ms: Optional[float] = None
    adapter: Optional[str] = None
    session: Optional[str] = None
    turn: Optional[int] = None
    schema: Optional[str] = None

    def to_json(self) -> dict:
        d = {"rid": self.rid, "arrival": self.arrival,
             "prompt": list(self.prompt),
             "max_new_tokens": self.max_new_tokens}
        if self.prefix_group is not None:
            d["prefix_group"] = self.prefix_group
        if self.cancel_after is not None:
            d["cancel_after"] = self.cancel_after
        if self.tenant is not None:
            d["tenant"] = self.tenant
        if self.priority:
            d["priority"] = self.priority
        if self.deadline_ms is not None:
            d["deadline_ms"] = self.deadline_ms
        if self.adapter is not None:
            d["adapter"] = self.adapter
        if self.session is not None:
            d["session"] = self.session
        if self.turn is not None:
            d["turn"] = self.turn
        if self.schema is not None:
            d["schema"] = self.schema
        return d

    @staticmethod
    def from_json(d: dict) -> "Request":
        return Request(rid=str(d["rid"]), arrival=float(d["arrival"]),
                       prompt=tuple(int(t) for t in d["prompt"]),
                       max_new_tokens=int(d["max_new_tokens"]),
                       prefix_group=d.get("prefix_group"),
                       cancel_after=d.get("cancel_after"),
                       tenant=d.get("tenant"),
                       priority=int(d.get("priority", 0)),
                       deadline_ms=d.get("deadline_ms"),
                       adapter=d.get("adapter"),
                       session=d.get("session"),
                       turn=(int(d["turn"]) if "turn" in d else None),
                       schema=d.get("schema"))

    def deadline_time(self) -> Optional[float]:
        """Absolute deadline in clock units (None when unbounded)."""
        if self.deadline_ms is None:
            return None
        return self.arrival + self.deadline_ms / 1000.0


def synthesize_trace(seed: int = 0, n_requests: int = 24, *,
                     arrival: str = "poisson",
                     mean_interarrival: float = 1.0,
                     burst_size: int = 4,
                     prompt_len: Tuple[int, int] = (4, 32),
                     output_len: Tuple[int, int] = (4, 16),
                     vocab_size: int = 128,
                     shared_prefix_frac: float = 0.0,
                     prefix_len: int = 8,
                     n_prefix_groups: int = 2,
                     churn_frac: float = 0.0,
                     rid_prefix: str = "req",
                     start: float = 0.0) -> List[Request]:
    """One seeded request stream. Deterministic in every field: the
    same (seed, knobs) always yields the identical trace.

    ``arrival``:
      - "poisson": exponential interarrival singles — steady mixed
        traffic (ragged lengths dominate the batch structure).
      - "bursty": Poisson-timed BURSTS of ``burst_size`` requests that
        arrive simultaneously with one shared prompt length per burst —
        the uniform-wave shape the dense compiled cache wins.

    ``shared_prefix_frac`` of requests join one of ``n_prefix_groups``
    cohorts whose prompts open with the group's fixed ``prefix_len``
    tokens (pass a page multiple to make whole prefix pages sharable).
    ``churn_frac`` of requests carry a ``cancel_after`` below their
    budget.
    """
    if arrival not in ("poisson", "bursty"):
        raise ValueError(f"arrival {arrival!r}: use 'poisson' or "
                         "'bursty'")
    if arrival == "bursty" and shared_prefix_frac > 0:
        # a per-request prefix bump would break the one-shared-length-
        # per-burst invariant (the dense-wave shape bursts exist for);
        # compose instead: merge_traces(bursty, poisson-with-prefixes)
        raise ValueError("bursty traces keep one prompt length per "
                         "burst; generate shared prefixes in a poisson "
                         "stream and merge_traces the two")
    rng = np.random.default_rng(seed)
    prefixes = [tuple(int(t) for t in rng.integers(
        1, vocab_size, prefix_len)) for _ in range(n_prefix_groups)]

    # arrival times first, so length/prefix draws can't perturb timing
    times: List[float] = []
    t = start
    if arrival == "poisson":
        for _ in range(n_requests):
            t += float(rng.exponential(mean_interarrival))
            times.append(t)
        burst_len = None
    else:
        burst_lens = []
        while len(times) < n_requests:
            t += float(rng.exponential(mean_interarrival * burst_size))
            n = min(burst_size, n_requests - len(times))
            times.extend([t] * n)
            burst_lens.extend(
                [int(rng.integers(prompt_len[0], prompt_len[1] + 1))] * n)
        burst_len = burst_lens

    reqs: List[Request] = []
    for i in range(n_requests):
        if burst_len is not None:
            plen = burst_len[i]
        else:
            plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        group = None
        if shared_prefix_frac > 0 and rng.random() < shared_prefix_frac:
            group = int(rng.integers(0, n_prefix_groups))
            plen = max(plen, prefix_len + 1)  # prefix + own tail
        tail = tuple(int(x) for x in rng.integers(
            1, vocab_size, plen - (prefix_len if group is not None
                                   else 0)))
        prompt = (prefixes[group] + tail) if group is not None else tail
        budget = int(rng.integers(output_len[0], output_len[1] + 1))
        cancel = None
        if churn_frac > 0 and budget > 1 and rng.random() < churn_frac:
            cancel = int(rng.integers(1, budget))
        reqs.append(Request(rid=f"{rid_prefix}{i}", arrival=times[i],
                            prompt=prompt, max_new_tokens=budget,
                            prefix_group=group, cancel_after=cancel))
    return reqs


DEFAULT_TENANTS = {
    # the three-tenant overload cast: an interactive tenant with tight
    # deadlines and a priority class above the rest, a standard tenant
    # with mixed deadlines, and one AGGRESSIVE bulk tenant that issues
    # bursts at twice everyone's share with loose deadlines — the
    # tenant fair queueing exists to contain.
    "intl": {"share": 0.30, "priority": 1, "burst": 1,
             "deadline": "tight"},
    "std": {"share": 0.30, "priority": 0, "burst": 1,
            "deadline": "mix"},
    "bulk": {"share": 0.40, "priority": 0, "burst": 4,
             "deadline": "loose"},
}


def synthesize_overload_trace(seed: int = 0, n_requests: int = 48, *,
                              service_tokens_per_unit: float = 4.0,
                              overload: float = 2.0,
                              tenants: Optional[dict] = None,
                              prompt_len: Tuple[int, int] = (4, 12),
                              output_len: Tuple[int, int] = (4, 12),
                              vocab_size: int = 128,
                              unit_ms: float = 1000.0,
                              tight_slack: float = 2.5,
                              loose_slack: float = 10.0,
                              rid_prefix: str = "q",
                              start: float = 0.0) -> List[Request]:
    """A seeded multi-tenant OVERLOAD trace: total demanded decode
    tokens arrive at ``overload`` x the engine's service rate, so a
    FIFO queue must grow without bound and only a scheduler that sheds
    or reorders can protect anyone's SLO.

    ``service_tokens_per_unit`` is the engine's decode capacity in
    tokens per clock unit (``slots * decode_chunk / decode_cost`` for a
    fixed-cost clock); arrival times are scaled so the trace's total
    output budget divided by its span equals ``overload`` x that rate.

    ``tenants`` maps name -> {share, priority, burst, deadline} (see
    ``DEFAULT_TENANTS``). ``burst > 1`` makes that tenant aggressive:
    its requests land in simultaneous bursts of that size. ``deadline``
    is "tight" / "loose" / "mix"; per-request ``deadline_ms`` is
    ``(1 + budget) * unit_ms * slack`` — the ideal lone-request service
    time (one prefill unit + one decode unit per token) times the
    cohort's slack. rids end in ".tight" / ".loose" so benches can
    split cohorts without a side channel.

    Deterministic in every field: same (seed, knobs) -> same trace.
    """
    spec = tenants if tenants is not None else DEFAULT_TENANTS
    if not spec:
        raise ValueError("need at least one tenant")
    rng = np.random.default_rng(seed)
    names = sorted(spec)
    # integer request counts per tenant, largest-share tenants absorb
    # the rounding remainder (deterministic)
    shares = np.asarray([float(spec[n].get("share", 1.0))
                         for n in names])
    shares = shares / shares.sum()
    counts = np.floor(shares * n_requests).astype(int)
    order = np.argsort(-shares)
    k = 0
    while counts.sum() < n_requests:
        counts[order[k % len(names)]] += 1
        k += 1

    # draw budgets first so the span can be sized to the demanded work
    budgets = {n: [int(rng.integers(output_len[0], output_len[1] + 1))
                   for _ in range(counts[i])]
               for i, n in enumerate(names)}
    total_tokens = sum(sum(b) for b in budgets.values())
    span = total_tokens / (overload * service_tokens_per_unit)

    reqs: List[Request] = []
    for i, name in enumerate(names):
        cfg = spec[name]
        n_t = int(counts[i])
        if n_t == 0:
            continue
        burst = max(1, int(cfg.get("burst", 1)))
        # a Poisson process conditioned on N arrivals in [0, span] IS
        # N sorted uniforms; bursty tenants share one draw per burst
        n_bursts = -(-n_t // burst)
        burst_times = np.sort(rng.uniform(0.0, span, n_bursts))
        times = np.repeat(burst_times, burst)[:n_t]
        mode = cfg.get("deadline", "mix")
        for j in range(n_t):
            plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
            prompt = tuple(int(t) for t in rng.integers(
                1, vocab_size, plen))
            budget = budgets[name][j]
            tight = {"tight": True, "loose": False}.get(
                mode, None)
            if tight is None:
                tight = bool(rng.random() < 0.5)
            slack = tight_slack if tight else loose_slack
            cohort = "tight" if tight else "loose"
            reqs.append(Request(
                rid=f"{rid_prefix}-{name}{j}.{cohort}",
                arrival=start + float(times[j]), prompt=prompt,
                max_new_tokens=budget, tenant=name,
                priority=int(cfg.get("priority", 0)),
                deadline_ms=round((1 + budget) * unit_ms * slack, 3)))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def synthesize_recurring_prefix_trace(seed: int = 0, *,
                                      n_cohorts: int = 2,
                                      cohort_size: int = 4,
                                      rounds: int = 3,
                                      prefix_len: int = 24,
                                      tail_len: Tuple[int, int] = (2, 8),
                                      output_len: Tuple[int, int]
                                      = (4, 8),
                                      vocab_size: int = 128,
                                      round_gap: float = 60.0,
                                      intra_gap: float = 0.5,
                                      rid_prefix: str = "p",
                                      start: float = 0.0,
                                      tag_groups: bool = False) \
        -> List[Request]:
    """The recurring-system-prompt workload — the dominant production
    shape automatic prefix caching exists for. ``n_cohorts`` system
    prompts (fixed ``prefix_len`` tokens each; pass a page multiple so
    whole pages are sharable) are each re-queried by ``cohort_size``
    requests per round, for ``rounds`` rounds.

    Rounds are separated by ``round_gap`` clock units — sized far past
    a round's service time — so LIVENESS-only sharing (prefix pages
    alive only while a sharer still holds them, the PR-2 behavior)
    gets ZERO cross-round hits: only RETENTION (evictable LRU pages
    surviving refcount 0) can serve round >= 2 from cache. Within a
    round, requests arrive ``intra_gap`` apart, interleaved across
    cohorts.

    rids are ``{rid_prefix}-r<round>c<cohort>.<i>`` (rounds 1-based)
    so benches can split rounds without a side channel.
    ``prefix_group`` stays None unless ``tag_groups`` — automatic
    caching needs no tag; the tag only feeds the router's
    shared_prefix signal. Deterministic in every field."""
    if prefix_len < 1 or rounds < 1 or n_cohorts < 1 or cohort_size < 1:
        raise ValueError("need >= 1 cohort, round, member and prefix "
                         "token")
    rng = np.random.default_rng(seed)
    prefixes = [tuple(int(t) for t in rng.integers(
        1, vocab_size, prefix_len)) for _ in range(n_cohorts)]
    reqs: List[Request] = []
    for rnd in range(1, rounds + 1):
        t0 = start + (rnd - 1) * round_gap
        for i in range(cohort_size):
            for c in range(n_cohorts):
                tail = tuple(int(x) for x in rng.integers(
                    1, vocab_size,
                    int(rng.integers(tail_len[0], tail_len[1] + 1))))
                budget = int(rng.integers(output_len[0],
                                          output_len[1] + 1))
                reqs.append(Request(
                    rid=f"{rid_prefix}-r{rnd}c{c}.{i}",
                    arrival=t0 + (i * n_cohorts + c) * intra_gap,
                    prompt=prefixes[c] + tail,
                    max_new_tokens=budget,
                    prefix_group=c if tag_groups else None))
    return reqs


def synthesize_cluster_trace(seed: int = 0,
                             n_requests: int = 100_000, *,
                             service_tokens_per_unit: float = 7.5,
                             overload: float = 1.7,
                             tenants: Optional[dict] = None,
                             n_cohorts: int = 24,
                             prefix_len: int = 32,
                             cohort_frac: float = 0.8,
                             cohort_skew: float = 1.1,
                             tail_len: Tuple[int, int] = (2, 8),
                             output_len: Tuple[int, int] = (4, 12),
                             vocab_size: int = 509,
                             unit_ms: float = 1000.0,
                             chunk_tokens: int = 8,
                             tight_slack: float = 2.0,
                             loose_slack: float = 6.0,
                             rid_prefix: str = "c",
                             start: float = 0.0) -> List[Request]:
    """The cluster-scale workload: ~10^5 requests of multi-tenant
    OVERLOAD traffic whose prompts are dominated by shared-prefix
    cohorts — the shape where prefix-aware placement earns its keep.

    ``service_tokens_per_unit`` is the CLUSTER's decode capacity
    (``n_replicas * slots * decode_chunk / decode_cost`` on a fixed
    clock); arrivals are scaled so demanded output tokens land at
    ``overload`` x that rate — enough pressure that placement quality
    converts into goodput, not just latency.

    ``cohort_frac`` of requests open with one of ``n_cohorts`` fixed
    ``prefix_len``-token system prompts; cohort choice is SKEWED by a
    Zipf-like law (weight ``1/(rank+1)^cohort_skew``) so hot cohorts
    dominate, exactly like production system prompts. Sized right
    (total cohort prefix pages >> one replica's retention slack,
    per-replica share of cohorts <= that slack), round-robin placement
    makes every replica serve every cohort and thrash its retention
    LRU, while prefix-aware placement partitions cohorts across
    replicas and hits. Solo prompts draw a random prefix-length body
    plus the same tail distribution, so cohort and solo requests load
    the engine identically.

    Tenants follow ``DEFAULT_TENANTS`` semantics (share / priority /
    burst / deadline mode); per-request ``deadline_ms`` is
    ``(ceil(prompt/chunk_tokens) + budget + 1) * unit_ms * slack`` —
    the lone-request service estimate under per-chunk prefill pricing
    times the cohort's slack. rids are
    ``{rid_prefix}-{tenant}{i}.k{cohort|solo}.{tight|loose}`` so
    benches can split cohorts and SLO classes without a side channel.
    Deterministic in every field: same (seed, knobs) -> same trace.
    """
    spec = tenants if tenants is not None else DEFAULT_TENANTS
    if not spec:
        raise ValueError("need at least one tenant")
    if not 0.0 <= cohort_frac <= 1.0:
        raise ValueError("cohort_frac must be in [0, 1]")
    if n_cohorts < 1 or prefix_len < 1:
        raise ValueError("need >= 1 cohort and >= 1 prefix token")
    rng = np.random.default_rng(seed)
    prefixes = [tuple(int(t) for t in rng.integers(
        1, vocab_size, prefix_len)) for _ in range(n_cohorts)]
    cw = np.asarray([1.0 / (c + 1) ** cohort_skew
                     for c in range(n_cohorts)])
    cw = cw / cw.sum()

    names = sorted(spec)
    shares = np.asarray([float(spec[n].get("share", 1.0))
                         for n in names])
    shares = shares / shares.sum()
    counts = np.floor(shares * n_requests).astype(int)
    order = np.argsort(-shares)
    k = 0
    while counts.sum() < n_requests:
        counts[order[k % len(names)]] += 1
        k += 1

    budgets = {n: [int(rng.integers(output_len[0], output_len[1] + 1))
                   for _ in range(counts[i])]
               for i, n in enumerate(names)}
    total_tokens = sum(sum(b) for b in budgets.values())
    span = total_tokens / (overload * service_tokens_per_unit)

    reqs: List[Request] = []
    for i, name in enumerate(names):
        cfg = spec[name]
        n_t = int(counts[i])
        if n_t == 0:
            continue
        burst = max(1, int(cfg.get("burst", 1)))
        n_bursts = -(-n_t // burst)
        burst_times = np.sort(rng.uniform(0.0, span, n_bursts))
        times = np.repeat(burst_times, burst)[:n_t]
        mode = cfg.get("deadline", "mix")
        for j in range(n_t):
            tlen = int(rng.integers(tail_len[0], tail_len[1] + 1))
            tail = tuple(int(t) for t in rng.integers(
                1, vocab_size, tlen))
            if cohort_frac > 0 and rng.random() < cohort_frac:
                c = int(rng.choice(n_cohorts, p=cw))
                prompt = prefixes[c] + tail
                ctag = f"k{c}"
            else:
                body = tuple(int(t) for t in rng.integers(
                    1, vocab_size, prefix_len))
                prompt = body + tail
                ctag = "solo"
            budget = budgets[name][j]
            tight = {"tight": True, "loose": False}.get(mode, None)
            if tight is None:
                tight = bool(rng.random() < 0.5)
            slack = tight_slack if tight else loose_slack
            cohort = "tight" if tight else "loose"
            chunks = -(-len(prompt) // chunk_tokens)
            reqs.append(Request(
                rid=f"{rid_prefix}-{name}{j}.{ctag}.{cohort}",
                arrival=start + float(times[j]), prompt=prompt,
                max_new_tokens=budget, tenant=name,
                priority=int(cfg.get("priority", 0)),
                deadline_ms=round((chunks + budget + 1) * unit_ms
                                  * slack, 3)))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def synthesize_prefill_heavy_trace(seed: int = 0, *,
                                   n_short: int = 48,
                                   n_long: int = 12,
                                   short_gap: float = 5.0,
                                   short_prompt: Tuple[int, int]
                                   = (5, 8),
                                   short_output: Tuple[int, int]
                                   = (24, 32),
                                   long_prompt: Tuple[int, int]
                                   = (48, 64),
                                   long_output: Tuple[int, int]
                                   = (4, 8),
                                   burst_size: int = 4,
                                   burst_gap: float = 60.0,
                                   first_burst: float = 10.0,
                                   vocab_size: int = 128,
                                   rid_prefix: str = "h",
                                   start: float = 0.0) \
        -> List[Request]:
    """The ADVERSARIAL shape for an interleaved prefill/decode loop:
    a steady stream of short-prompt, long-budget requests (they fill
    the decode slots and stay mid-decode) punctuated by BURSTS of
    long, mostly-uncached prompts (every prompt body is an
    independent draw — nothing for the prefix cache to serve). Each
    burst's prefill chunks are what stall every active decode slot
    when prefill monopolizes the turn; the async prefill lane (and
    cluster-level disaggregation) exists to make TPOT independent of
    exactly this queue.

    rids end in ``.short`` / ``.long`` so benches can split the
    mid-decode cohort (whose TPOT the burst torches) from the burst
    cohort (whose prefill does the torching) without a side channel.
    Defaults are sized for a slots=8 / decode_chunk=4 engine on the
    unit-cost fixed clock at ~80%% utilization — loaded enough that
    bursts land while every slot decodes, slack enough that queueing
    does not drown the phase split. Deterministic in every field;
    JSONL round-trips through ``save_trace``/``load_trace`` like
    every other synthesizer."""
    if n_short < 1 or n_long < 0 or burst_size < 1:
        raise ValueError("need >= 1 short request and a >= 1 burst "
                         "size")
    rng = np.random.default_rng(seed)
    reqs: List[Request] = []
    t = start
    for i in range(n_short):
        t += short_gap
        plen = int(rng.integers(short_prompt[0], short_prompt[1] + 1))
        reqs.append(Request(
            rid=f"{rid_prefix}-s{i:03d}.short", arrival=t,
            prompt=tuple(int(x) for x in rng.integers(
                1, vocab_size, plen)),
            max_new_tokens=int(rng.integers(short_output[0],
                                            short_output[1] + 1))))
    k = 0
    b = 0
    while k < n_long:
        tb = start + first_burst + b * burst_gap
        for j in range(burst_size):
            if k >= n_long:
                break
            plen = int(rng.integers(long_prompt[0],
                                    long_prompt[1] + 1))
            reqs.append(Request(
                rid=f"{rid_prefix}-l{b}.{j}.long", arrival=tb,
                prompt=tuple(int(x) for x in rng.integers(
                    1, vocab_size, plen)),
                max_new_tokens=int(rng.integers(long_output[0],
                                                long_output[1] + 1))))
            k += 1
        b += 1
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def synthesize_admission_burst_trace(seed: int = 0, *,
                                     n_bursts: int = 3,
                                     burst_size: int = 8,
                                     burst_gap: float = 80.0,
                                     first_burst: float = 16.0,
                                     burst_prompt: Tuple[int, int]
                                     = (28, 32),
                                     burst_output: Tuple[int, int]
                                     = (2, 4),
                                     n_background: int = 12,
                                     background_gap: float = 4.0,
                                     background_prompt: Tuple[int, int]
                                     = (3, 6),
                                     background_output: Tuple[int, int]
                                     = (48, 64),
                                     vocab_size: int = 128,
                                     rid_prefix: str = "ab",
                                     start: float = 0.0) \
        -> List[Request]:
    """SYNCHRONIZED arrival spikes: every request of a burst arrives
    at the SAME instant, so a per-chunk prefill lane must serialize
    ``burst_size`` independent long prompts one bounded call at a
    time — the shape whose TTFT a ragged batched prefill divides by
    the batching factor (all lane rows ride ONE fused program per
    turn). A background cohort of short-prompt, long-budget requests
    keeps the decode slots busy so each serialized chunk turn also
    pays for a decode batch, exactly the contention the fused lane
    amortizes.

    The burst factor is named in the rids — burst rows end in
    ``.x{burst_size}`` (e.g. ``ab-b0.03.x8``) and background rows in
    ``.bg`` — so benches split the spike cohort (the TTFT claim) from
    the steady cohort without a side channel. Deterministic in every
    field; JSONL round-trips through ``save_trace``/``load_trace``
    like every other synthesizer."""
    if n_bursts < 1 or burst_size < 1 or n_background < 0:
        raise ValueError("need >= 1 burst of >= 1 request")
    rng = np.random.default_rng(seed)
    reqs: List[Request] = []
    t = start
    for i in range(n_background):
        t += background_gap
        plen = int(rng.integers(background_prompt[0],
                                background_prompt[1] + 1))
        reqs.append(Request(
            rid=f"{rid_prefix}-g{i:03d}.bg", arrival=t,
            prompt=tuple(int(x) for x in rng.integers(
                1, vocab_size, plen)),
            max_new_tokens=int(rng.integers(background_output[0],
                                            background_output[1]
                                            + 1))))
    for b in range(n_bursts):
        tb = start + first_burst + b * burst_gap
        for j in range(burst_size):
            plen = int(rng.integers(burst_prompt[0],
                                    burst_prompt[1] + 1))
            reqs.append(Request(
                rid=f"{rid_prefix}-b{b}.{j:02d}.x{burst_size}",
                arrival=tb,
                prompt=tuple(int(x) for x in rng.integers(
                    1, vocab_size, plen)),
                max_new_tokens=int(rng.integers(burst_output[0],
                                                burst_output[1]
                                                + 1))))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def synthesize_zipf_adapter_trace(seed: int = 0,
                                  n_requests: int = 2000, *,
                                  n_adapters: int = 4,
                                  adapter_skew: float = 1.1,
                                  base_frac: float = 0.0,
                                  service_tokens_per_unit: float = 8.0,
                                  overload: float = 1.4,
                                  prompt_len: Tuple[int, int] = (4, 12),
                                  output_len: Tuple[int, int] = (4, 12),
                                  churn_frac: float = 0.05,
                                  vocab_size: int = 509,
                                  unit_ms: float = 1000.0,
                                  slack: float = 6.0,
                                  chunk_tokens: int = 8,
                                  rid_prefix: str = "L",
                                  start: float = 0.0) -> List[Request]:
    """The MULTI-MODEL workload: mixed-churn traffic whose requests
    each name one of ``n_adapters`` LoRA adapters, popularity SKEWED
    by a Zipf-like law (weight ``1/(rank+1)^adapter_skew``) — exactly
    how production fine-tune traffic concentrates on a few hot
    variants while a long tail stays warm. ``base_frac`` of requests
    carry ``adapter=None`` (base-model traffic riding the same
    batches through the identity slot).

    Arrivals are sorted uniforms over a span sized so demanded output
    tokens land at ``overload`` x ``service_tokens_per_unit`` (the
    multiplexed engine's capacity): hot-adapter demand alone then
    exceeds any single dedicated replica's share, which is the gap
    the one-engine-per-adapter split loses goodput to and adapter
    multiplexing recovers. ``churn_frac`` of requests carry a
    ``cancel_after`` below budget (the mixed-churn shape — adapter
    pins must survive mid-stream eviction). Every request gets a
    loose ``deadline_ms`` (lone-request per-chunk service estimate x
    ``slack``) so goodput is deadline-honest.

    Adapter ids are BAKED INTO rids — ``{rid_prefix}-00042.a3`` /
    ``...base`` — so a gate can audit per-adapter routing and parity
    without a side channel; the adapter NAME is ``a<k>``.
    Deterministic in every field; JSONL round-trips via
    ``save_trace``/``load_trace``."""
    if n_adapters < 1:
        raise ValueError("need >= 1 adapter")
    if not 0.0 <= base_frac <= 1.0:
        raise ValueError("base_frac must be in [0, 1]")
    if adapter_skew < 0:
        raise ValueError("adapter_skew must be >= 0")
    rng = np.random.default_rng(seed)
    w = np.asarray([1.0 / (k + 1) ** adapter_skew
                    for k in range(n_adapters)])
    w = w / w.sum()
    budgets = [int(rng.integers(output_len[0], output_len[1] + 1))
               for _ in range(n_requests)]
    span = sum(budgets) / (overload * service_tokens_per_unit)
    times = np.sort(rng.uniform(0.0, span, n_requests))
    reqs: List[Request] = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        prompt = tuple(int(t) for t in rng.integers(1, vocab_size,
                                                    plen))
        budget = budgets[i]
        if base_frac > 0 and rng.random() < base_frac:
            adapter, tag = None, "base"
        else:
            k = int(rng.choice(n_adapters, p=w))
            adapter, tag = f"a{k}", f"a{k}"
        cancel = None
        if churn_frac > 0 and budget > 1 \
                and rng.random() < churn_frac:
            cancel = int(rng.integers(1, budget))
        chunks = -(-plen // chunk_tokens)
        reqs.append(Request(
            rid=f"{rid_prefix}-{i:05d}.{tag}",
            arrival=start + float(times[i]), prompt=prompt,
            max_new_tokens=budget, cancel_after=cancel,
            deadline_ms=round((chunks + budget + 1) * unit_ms
                              * slack, 3),
            adapter=adapter))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def synthesize_schema_trace(seed: int = 0, n_requests: int = 2000, *,
                            n_schemas: int = 4,
                            schema_skew: float = 1.1,
                            free_frac: float = 0.25,
                            service_tokens_per_unit: float = 8.0,
                            overload: float = 1.4,
                            prompt_len: Tuple[int, int] = (4, 12),
                            output_len: Tuple[int, int] = (24, 48),
                            vocab_size: int = 509,
                            unit_ms: float = 1000.0,
                            slack: float = 6.0,
                            chunk_tokens: int = 8,
                            rid_prefix: str = "G",
                            start: float = 0.0) -> List[Request]:
    """The STRUCTURED-OUTPUT workload: traffic whose requests each
    name one of ``n_schemas`` grammars (constrained decoding),
    popularity SKEWED by a Zipf-like law (weight
    ``1/(rank+1)^schema_skew``) — production tool-call traffic
    concentrates on a few hot schemas while a long tail stays warm,
    which is exactly the shape the budgeted ``GrammarCache`` serves
    with one compile per schema. ``free_frac`` of requests carry
    ``schema=None`` (free-running streams riding the same batches
    through the all-allow state).

    Arrivals are sorted uniforms over a span sized so demanded output
    tokens land at ``overload`` x ``service_tokens_per_unit``; output
    budgets are generous (``output_len`` high) because a constrained
    stream self-terminates when its automaton accepts — the budget is
    a ceiling, not the expected length. Every request gets a loose
    ``deadline_ms`` so goodput stays deadline-honest.

    Schema ids are BAKED INTO rids — ``{rid_prefix}-00042.s3`` /
    ``...free`` — so a gate can audit per-schema routing and free-row
    parity without a side channel; the schema NAME is ``s<k>``.
    Deterministic in every field; JSONL round-trips via
    ``save_trace``/``load_trace``."""
    if n_schemas < 1:
        raise ValueError("need >= 1 schema")
    if not 0.0 <= free_frac <= 1.0:
        raise ValueError("free_frac must be in [0, 1]")
    if schema_skew < 0:
        raise ValueError("schema_skew must be >= 0")
    rng = np.random.default_rng(seed)
    w = np.asarray([1.0 / (k + 1) ** schema_skew
                    for k in range(n_schemas)])
    w = w / w.sum()
    budgets = [int(rng.integers(output_len[0], output_len[1] + 1))
               for _ in range(n_requests)]
    span = sum(budgets) / (overload * service_tokens_per_unit)
    times = np.sort(rng.uniform(0.0, span, n_requests))
    reqs: List[Request] = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        prompt = tuple(int(t) for t in rng.integers(1, vocab_size,
                                                    plen))
        budget = budgets[i]
        if free_frac > 0 and rng.random() < free_frac:
            schema, tag = None, "free"
        else:
            k = int(rng.choice(n_schemas, p=w))
            schema, tag = f"s{k}", f"s{k}"
        chunks = -(-plen // chunk_tokens)
        reqs.append(Request(
            rid=f"{rid_prefix}-{i:05d}.{tag}",
            arrival=start + float(times[i]), prompt=prompt,
            max_new_tokens=budget,
            deadline_ms=round((chunks + budget + 1) * unit_ms
                              * slack, 3),
            schema=schema))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def synthesize_session_trace(seed: int = 0, n_sessions: int = 8, *,
                             turns: int = 3,
                             think_time: float = 40.0,
                             first_prompt_len: Tuple[int, int]
                             = (16, 32),
                             turn_prompt_len: Tuple[int, int] = (4, 8),
                             output_len: Tuple[int, int] = (4, 8),
                             vocab_size: int = 128,
                             mean_interarrival: float = 2.0,
                             rid_prefix: str = "s",
                             start: float = 0.0) -> List[Request]:
    """The MULTI-TURN workload — the real shape of million-user chat
    traffic, and what the KV memory hierarchy is gated on. Each of
    ``n_sessions`` conversations opens with a ``first_prompt_len``
    prompt, then issues ``turns - 1`` follow-ups: turn ``k``'s prompt
    is turn ``k-1``'s prompt EXTENDED by fresh ``turn_prompt_len``
    tokens, arriving an exponential ``think_time`` gap after the
    previous turn — long enough (size it far past a turn's service
    time) that the session's prefix pages have left the running set
    and only the retention LRU or the host arena can serve round 2
    from cache instead of recomputing.

    Session openers arrive ``mean_interarrival`` apart (exponential),
    so sessions overlap and the resident pool must juggle many cold
    prefixes at once — the pressure that makes spill-to-host pay.
    rids are ``{rid_prefix}{j}.t{k}`` (turns 1-based) and every
    request carries ``session={rid_prefix}{j}``/``turn=k``, so
    benches split turn cohorts without a side channel. Deterministic
    in every field; JSONL round-trips via ``save_trace``/
    ``load_trace`` (legacy session-less traces stay byte-identical —
    the keys are emitted only when set)."""
    if n_sessions < 1 or turns < 1:
        raise ValueError("need >= 1 session of >= 1 turn")
    if think_time <= 0 or mean_interarrival <= 0:
        raise ValueError("think_time and mean_interarrival must be "
                         "> 0")
    rng = np.random.default_rng(seed)
    reqs: List[Request] = []
    t0 = start
    for j in range(n_sessions):
        t0 += float(rng.exponential(mean_interarrival))
        sid = f"{rid_prefix}{j}"
        plen = int(rng.integers(first_prompt_len[0],
                                first_prompt_len[1] + 1))
        prompt = tuple(int(x) for x in rng.integers(1, vocab_size,
                                                    plen))
        t = t0
        for k in range(1, turns + 1):
            if k > 1:
                t += float(rng.exponential(think_time))
                ext = int(rng.integers(turn_prompt_len[0],
                                       turn_prompt_len[1] + 1))
                prompt = prompt + tuple(
                    int(x) for x in rng.integers(1, vocab_size, ext))
            budget = int(rng.integers(output_len[0],
                                      output_len[1] + 1))
            reqs.append(Request(
                rid=f"{sid}.t{k}", arrival=t, prompt=prompt,
                max_new_tokens=budget, session=sid, turn=k))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def _profile_times(rng, n: int, span: float, shape) -> np.ndarray:
    """``n`` sorted arrival times over ``[0, span]`` drawn from an
    inhomogeneous Poisson process with relative rate ``shape`` (an
    array sampled on a uniform grid over the span): the standard
    time-rescaling construction — N arrivals conditioned on the span
    are N sorted uniforms over the CUMULATIVE intensity, mapped back
    through its inverse (piecewise-linear interpolation over the
    grid). Deterministic in (rng state, shape)."""
    shape = np.asarray(shape, float)
    if shape.ndim != 1 or len(shape) < 2 or (shape <= 0).any():
        raise ValueError("shape must be >= 2 strictly positive "
                         "relative-rate samples")
    grid = np.linspace(0.0, span, len(shape))
    cum = np.concatenate([[0.0], np.cumsum(
        (shape[1:] + shape[:-1]) * 0.5 * np.diff(grid))])
    u = np.sort(rng.uniform(0.0, cum[-1], n))
    return np.interp(u, cum, grid)


def _profiled_tenant_trace(rng, shape, span: float, *,
                           tenants: dict,
                           prompt_len: Tuple[int, int],
                           budgets: dict, counts, names,
                           vocab_size: int, unit_ms: float,
                           chunk_tokens: int, tight_slack: float,
                           loose_slack: float, rid_prefix: str,
                           start: float) -> List[Request]:
    """The shared tenant/deadline body of the rate-profiled traces:
    identical request semantics to ``synthesize_cluster_trace`` (per-
    chunk deadline pricing, tight/loose cohort rid tags, bursty
    tenants sharing one arrival draw per burst) with arrival times
    drawn from ``shape`` via ``_profile_times`` instead of a flat
    uniform — so a diurnal day and a flash crowd load the engine with
    the SAME request mix the overload gates are calibrated on, just
    on a different clock."""
    reqs: List[Request] = []
    for i, name in enumerate(names):
        cfg = tenants[name]
        n_t = int(counts[i])
        if n_t == 0:
            continue
        burst = max(1, int(cfg.get("burst", 1)))
        n_bursts = -(-n_t // burst)
        burst_times = _profile_times(rng, n_bursts, span, shape)
        times = np.repeat(burst_times, burst)[:n_t]
        mode = cfg.get("deadline", "mix")
        for j in range(n_t):
            plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
            prompt = tuple(int(t) for t in rng.integers(
                1, vocab_size, plen))
            budget = budgets[name][j]
            tight = {"tight": True, "loose": False}.get(mode, None)
            if tight is None:
                tight = bool(rng.random() < 0.5)
            slack = tight_slack if tight else loose_slack
            cohort = "tight" if tight else "loose"
            chunks = -(-len(prompt) // chunk_tokens)
            reqs.append(Request(
                rid=f"{rid_prefix}-{name}{j}.{cohort}",
                arrival=start + float(times[j]), prompt=prompt,
                max_new_tokens=budget, tenant=name,
                priority=int(cfg.get("priority", 0)),
                deadline_ms=round((chunks + budget + 1) * unit_ms
                                  * slack, 3)))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def _tenant_counts_budgets(rng, spec, n_requests, output_len):
    """The deterministic per-tenant request-count and budget draws
    every overload-family synthesizer shares (largest-share tenants
    absorb the rounding remainder; budgets drawn FIRST so the span
    can be sized to the demanded work)."""
    names = sorted(spec)
    shares = np.asarray([float(spec[n].get("share", 1.0))
                         for n in names])
    shares = shares / shares.sum()
    counts = np.floor(shares * n_requests).astype(int)
    order = np.argsort(-shares)
    k = 0
    while counts.sum() < n_requests:
        counts[order[k % len(names)]] += 1
        k += 1
    budgets = {n: [int(rng.integers(output_len[0], output_len[1] + 1))
                   for _ in range(counts[i])]
               for i, n in enumerate(names)}
    return names, counts, budgets


def synthesize_diurnal_trace(seed: int = 0,
                             n_requests: int = 100_000, *,
                             service_tokens_per_unit: float = 25.0,
                             peak_overload: float = 1.05,
                             trough: float = 0.2,
                             days: float = 1.0,
                             tenants: Optional[dict] = None,
                             prompt_len: Tuple[int, int] = (4, 12),
                             output_len: Tuple[int, int] = (4, 12),
                             vocab_size: int = 509,
                             unit_ms: float = 1000.0,
                             chunk_tokens: int = 8,
                             tight_slack: float = 2.0,
                             loose_slack: float = 6.0,
                             rid_prefix: str = "d",
                             start: float = 0.0,
                             grid: int = 2048) -> List[Request]:
    """The DIURNAL workload: arrival rate follows a day cycle —
    ``rate(x) = trough + (1 - trough) * sin(pi * days * x)^2`` over
    the span (``days`` full trough->peak->trough cycles; peak 1.0 at
    mid-cycle, ``trough`` at the edges). The span is sized so the
    PEAK instantaneous token demand equals ``peak_overload`` x
    ``service_tokens_per_unit`` (the fleet capacity the trace is
    aimed at): a fleet sized to the peak idles most of the day, a
    fleet sized to the mean burns its error budget every peak — the
    exact gap elastic autoscaling exists to close, and the virtual
    clock makes a 10^5-request "day" cheap.

    Tenants/deadlines/rids follow ``synthesize_cluster_trace``'s
    semantics (per-chunk deadline pricing, ``.tight``/``.loose``
    cohort tags). Deterministic in every field; JSONL round-trips via
    ``save_trace``/``load_trace``."""
    if not 0.0 < trough <= 1.0:
        raise ValueError("trough is a relative rate in (0, 1]")
    if peak_overload <= 0 or days <= 0:
        raise ValueError("peak_overload and days must be > 0")
    spec = tenants if tenants is not None else DEFAULT_TENANTS
    if not spec:
        raise ValueError("need at least one tenant")
    rng = np.random.default_rng(seed)
    names, counts, budgets = _tenant_counts_budgets(
        rng, spec, n_requests, output_len)
    total_tokens = sum(sum(b) for b in budgets.values())
    xs = np.linspace(0.0, 1.0, grid)
    shape = trough + (1.0 - trough) * np.sin(np.pi * days * xs) ** 2
    mean_f, peak_f = float(shape.mean()), float(shape.max())
    # peak token rate = (T / (mean_f * span)) * peak_f == po * cap
    span = total_tokens * peak_f \
        / (mean_f * peak_overload * service_tokens_per_unit)
    return _profiled_tenant_trace(
        rng, shape, span, tenants=spec,
        prompt_len=prompt_len, budgets=budgets,
        counts=counts, names=names, vocab_size=vocab_size,
        unit_ms=unit_ms, chunk_tokens=chunk_tokens,
        tight_slack=tight_slack, loose_slack=loose_slack,
        rid_prefix=rid_prefix, start=start)


def synthesize_flash_crowd_trace(seed: int = 0,
                                 n_requests: int = 100_000, *,
                                 service_tokens_per_unit: float = 25.0,
                                 base_overload: float = 0.55,
                                 spikes: Tuple[Tuple[float, float,
                                                     float], ...]
                                 = ((0.55, 0.06, 3.5),),
                                 tenants: Optional[dict] = None,
                                 prompt_len: Tuple[int, int] = (4, 12),
                                 output_len: Tuple[int, int] = (4, 12),
                                 vocab_size: int = 509,
                                 unit_ms: float = 1000.0,
                                 chunk_tokens: int = 8,
                                 tight_slack: float = 2.0,
                                 loose_slack: float = 6.0,
                                 rid_prefix: str = "f",
                                 start: float = 0.0,
                                 grid: int = 2048) -> List[Request]:
    """The FLASH-CROWD workload: a steady base rate (sized so base
    token demand = ``base_overload`` x ``service_tokens_per_unit`` —
    comfortably under capacity) punctuated by sudden rate spikes.
    Each spike is ``(t0_frac, dur_frac, magnitude)``: from ``t0_frac``
    of the span, for ``dur_frac`` of it, the rate multiplies by
    ``magnitude`` — the viral-moment shape no static fleet sized to
    NORMAL traffic survives, and the detect->act loop's reaction-time
    test (a burn-rate incident opens inside the spike; the join must
    land before the budget is gone).

    Same tenant/deadline semantics as the diurnal trace.
    Deterministic in every field; JSONL round-trips."""
    if base_overload <= 0:
        raise ValueError("base_overload must be > 0")
    for t0, dur, mag in spikes:
        if not (0.0 <= t0 < 1.0 and 0.0 < dur <= 1.0 and mag >= 1.0):
            raise ValueError("each spike is (t0_frac in [0,1), "
                             "dur_frac in (0,1], magnitude >= 1)")
    spec = tenants if tenants is not None else DEFAULT_TENANTS
    if not spec:
        raise ValueError("need at least one tenant")
    rng = np.random.default_rng(seed)
    names, counts, budgets = _tenant_counts_budgets(
        rng, spec, n_requests, output_len)
    total_tokens = sum(sum(b) for b in budgets.values())
    xs = np.linspace(0.0, 1.0, grid)
    shape = np.ones_like(xs)
    for t0, dur, mag in spikes:
        # multiplicative, as documented: overlapping spikes compound
        # (a single spike from the base rate is identical either way)
        shape = np.where((xs >= t0) & (xs < t0 + dur),
                         shape * mag, shape)
    mean_f = float(shape.mean())
    # BASE token rate (relative rate 1.0) == base_overload * cap
    span = total_tokens \
        / (mean_f * base_overload * service_tokens_per_unit)
    return _profiled_tenant_trace(
        rng, shape, span, tenants=spec,
        prompt_len=prompt_len, budgets=budgets,
        counts=counts, names=names, vocab_size=vocab_size,
        unit_ms=unit_ms, chunk_tokens=chunk_tokens,
        tight_slack=tight_slack, loose_slack=loose_slack,
        rid_prefix=rid_prefix, start=start)


def synthesize_deadline_mix_trace(seed: int = 0,
                                  n_requests: int = 160, *,
                                  service_tokens_per_unit: float = 8.0,
                                  base_load: float = 0.6,
                                  surge: Tuple[float, float, float]
                                  = (0.5, 0.18, 4.0),
                                  loose_frac: float = 0.75,
                                  prompt_len: Tuple[int, int] = (4, 12),
                                  output_len: Tuple[int, int] = (4, 12),
                                  vocab_size: int = 509,
                                  unit_ms: float = 1000.0,
                                  chunk_tokens: int = 8,
                                  loose_slack: float = 6.0,
                                  tight_slack: float = 2.0,
                                  rid_prefix: str = "sx",
                                  start: float = 0.0,
                                  grid: int = 1024) -> List[Request]:
    """The SPECULATIVE-serving workload: a deadline/priority COHORT
    mix on a calm-then-surge arrival profile, sized so the adaptive
    spec rule exercises BOTH of its paths.

    - ``loose_frac`` of requests form the **loose** cohort (priority
      0, ``deadline_ms = (chunks + budget + 1) * unit_ms *
      loose_slack`` — comfortably above the default
      ``SpecConfig.loose_deadline_ms``): the traffic the per-request
      rule routes SPECULATIVE. The rest form the **tight** cohort
      (priority 1, ``tight_slack``): latency-critical rows the rule
      keeps on plain decode. The cohort is baked into the rid
      (``{rid_prefix}-0042.loose`` / ``.tight``) so benches and gates
      split them without a side channel.
    - The base arrival rate is sized to ``base_load`` x
      ``service_tokens_per_unit`` (comfortably under capacity — spec
      pays off and nothing burns); ``surge = (t0_frac, dur_frac,
      magnitude)`` multiplies the rate over that window of the span,
      pushing demand past capacity so deadlines miss, a
      ``BurnRateRule`` fires, and the overload fallback delivered
      through ``QoSScheduler.note_incident`` parks the spec route
      until the burn recovers.

    Deterministic in every field; JSONL round-trips via
    ``save_trace``/``load_trace`` like every other synthesizer."""
    if not 0.0 < base_load:
        raise ValueError("base_load must be > 0")
    if not 0.0 <= loose_frac <= 1.0:
        raise ValueError("loose_frac must be in [0, 1]")
    t0f, durf, mag = surge
    if not (0.0 <= t0f < 1.0 and 0.0 < durf <= 1.0 and mag >= 1.0):
        raise ValueError("surge is (t0_frac in [0,1), dur_frac in "
                         "(0,1], magnitude >= 1)")
    rng = np.random.default_rng(seed)
    budgets = [int(rng.integers(output_len[0], output_len[1] + 1))
               for _ in range(n_requests)]
    xs = np.linspace(0.0, 1.0, grid)
    shape = np.where((xs >= t0f) & (xs < t0f + durf),
                     float(mag), 1.0)
    mean_f = float(shape.mean())
    # BASE token rate (relative rate 1.0) == base_load * capacity
    span = sum(budgets) \
        / (mean_f * base_load * service_tokens_per_unit)
    times = _profile_times(rng, n_requests, span, shape)
    reqs: List[Request] = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        prompt = tuple(int(t) for t in rng.integers(1, vocab_size,
                                                    plen))
        budget = budgets[i]
        loose = bool(rng.random() < loose_frac)
        cohort = "loose" if loose else "tight"
        slack = loose_slack if loose else tight_slack
        chunks = -(-plen // chunk_tokens)
        reqs.append(Request(
            rid=f"{rid_prefix}-{i:04d}.{cohort}",
            arrival=start + float(times[i]), prompt=prompt,
            max_new_tokens=budget,
            priority=0 if loose else 1,
            deadline_ms=round((chunks + budget + 1) * unit_ms
                              * slack, 3)))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def merge_traces(*traces: Sequence[Request]) -> List[Request]:
    """Interleave traces by arrival time (rids must already be unique —
    give each source a distinct ``rid_prefix``)."""
    out = [r for tr in traces for r in tr]
    rids = [r.rid for r in out]
    if len(set(rids)) != len(rids):
        raise ValueError("merge_traces: duplicate rids across traces "
                         "(use distinct rid_prefix per source)")
    return sorted(out, key=lambda r: (r.arrival, r.rid))


def save_trace(path: str, trace: Sequence[Request]) -> None:
    with open(path, "w") as f:
        for r in trace:
            f.write(json.dumps(r.to_json()) + "\n")


def iter_jsonl_tolerant(path: str):
    """Stream a JSONL file's records, tolerating exactly the artifact
    a crashing writer leaves behind: a torn FINAL line warns and ends
    the stream at the valid prefix; a malformed line anywhere EARLIER
    — or a file with NO valid record at all — raises, because those
    mean the file is not what it claims, not that a writer died.
    One-record lookahead, so a 10^5-line incident log never
    materializes in memory. Shared by ``load_trace``,
    ``engine.load_engine_log`` and any future crash-tolerant loader."""
    import warnings
    prev = None  # (line number, text) not yet parsed
    n_ok = 0
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            ln = raw.strip()
            if not ln:
                continue
            if prev is not None:
                try:
                    d = json.loads(prev[1])
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"{path}: malformed JSONL at line {prev[0]} "
                        f"(not just a torn tail): {e}") from e
                n_ok += 1
                yield d
            prev = (i, ln)
    if prev is not None:
        try:
            d = json.loads(prev[1])
        except json.JSONDecodeError as e:
            if n_ok == 0:
                # nothing valid precedes the bad line: that is not a
                # torn tail, it is the wrong file — an empty "prefix"
                # has no evidentiary value and returning it silently
                # would let a mispointed path replay as an empty log
                raise ValueError(
                    f"{path}: no valid JSONL record (first line is "
                    f"malformed): {e}") from e
            warnings.warn(
                f"{path}: final JSONL line (line {prev[0]}) is "
                f"truncated — returning the {n_ok} valid records "
                f"before it (crash-written log?)")
            return
        yield d


def load_trace(path: str) -> List[Request]:
    """Load a ``save_trace`` JSONL. A torn FINAL line (the file a
    crashing writer leaves behind) loads with a warning and returns
    the valid prefix; a malformed line anywhere earlier still raises —
    that file is not a trace."""
    return [Request.from_json(d) for d in iter_jsonl_tolerant(path)]


def trace_stats(trace: Sequence[Request]) -> dict:
    """The shape summary a bench row carries next to its numbers."""
    if not trace:
        return {"n_requests": 0}
    plens = np.asarray([len(r.prompt) for r in trace])
    budgets = np.asarray([r.max_new_tokens for r in trace])
    arr = np.asarray([r.arrival for r in trace])
    out = {
        "n_requests": len(trace),
        "prompt_len_min": int(plens.min()),
        "prompt_len_max": int(plens.max()),
        "prompt_tokens": int(plens.sum()),
        "output_budget_tokens": int(budgets.sum()),
        "span": round(float(arr.max() - arr.min()), 4),
        "shared_prefix_requests": sum(
            1 for r in trace if r.prefix_group is not None),
        "churn_requests": sum(
            1 for r in trace if r.cancel_after is not None),
    }
    tenants = sorted({r.tenant for r in trace if r.tenant is not None})
    if tenants:
        out["tenants"] = tenants
    n_deadline = sum(1 for r in trace if r.deadline_ms is not None)
    if n_deadline:
        out["deadline_requests"] = n_deadline
    adapters = sorted({r.adapter for r in trace
                       if r.adapter is not None})
    if adapters:
        # only adapter-carrying traces grow these keys (adapter-less
        # stats stay byte-identical)
        out["adapters"] = adapters
        out["adapter_requests"] = sum(
            1 for r in trace if r.adapter is not None)
    sessions = sorted({r.session for r in trace
                       if r.session is not None})
    if sessions:
        # only session-carrying traces grow these keys (one-shot
        # trace stats stay byte-identical)
        out["sessions"] = len(sessions)
        out["session_turns"] = sum(
            1 for r in trace if r.session is not None)
    schemas = sorted({r.schema for r in trace
                      if r.schema is not None})
    if schemas:
        # only schema-carrying traces grow these keys (free-running
        # trace stats stay byte-identical)
        out["schemas"] = schemas
        out["schema_requests"] = sum(
            1 for r in trace if r.schema is not None)
    return out
