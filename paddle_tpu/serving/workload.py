"""Trace-driven serving workloads: seeded, replayable request streams.

The serving engine is only as honest as its load. A static-batch
microbench answers "how fast is one shape"; a server answers "how fast
is a STREAM" — requests arriving over time (Poisson singles, bursts),
ragged prompt/output lengths, shared system prompts, and mid-run churn
(clients disconnecting). ``synthesize_trace`` generates exactly that
mix from one seed, so the same workload replays bit-identically across
policies, runs, and machines; ``save_trace``/``load_trace`` round-trip
it as JSONL for pinned regression traces.
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Request:
    """One serving request as the trace records it.

    ``arrival`` is in the engine clock's units (seconds for a measured
    replay; abstract units under a fixed-cost clock). ``prefix_group``
    marks shared-system-prompt cohorts: every request in a group opens
    with the same token prefix, the prefix-cache case.
    ``cancel_after`` models churn — the client disconnects after that
    many generated tokens and the engine must evict mid-stream.
    """

    rid: str
    arrival: float
    prompt: Tuple[int, ...]
    max_new_tokens: int
    prefix_group: Optional[int] = None
    cancel_after: Optional[int] = None

    def to_json(self) -> dict:
        d = {"rid": self.rid, "arrival": self.arrival,
             "prompt": list(self.prompt),
             "max_new_tokens": self.max_new_tokens}
        if self.prefix_group is not None:
            d["prefix_group"] = self.prefix_group
        if self.cancel_after is not None:
            d["cancel_after"] = self.cancel_after
        return d

    @staticmethod
    def from_json(d: dict) -> "Request":
        return Request(rid=str(d["rid"]), arrival=float(d["arrival"]),
                       prompt=tuple(int(t) for t in d["prompt"]),
                       max_new_tokens=int(d["max_new_tokens"]),
                       prefix_group=d.get("prefix_group"),
                       cancel_after=d.get("cancel_after"))


def synthesize_trace(seed: int = 0, n_requests: int = 24, *,
                     arrival: str = "poisson",
                     mean_interarrival: float = 1.0,
                     burst_size: int = 4,
                     prompt_len: Tuple[int, int] = (4, 32),
                     output_len: Tuple[int, int] = (4, 16),
                     vocab_size: int = 128,
                     shared_prefix_frac: float = 0.0,
                     prefix_len: int = 8,
                     n_prefix_groups: int = 2,
                     churn_frac: float = 0.0,
                     rid_prefix: str = "req",
                     start: float = 0.0) -> List[Request]:
    """One seeded request stream. Deterministic in every field: the
    same (seed, knobs) always yields the identical trace.

    ``arrival``:
      - "poisson": exponential interarrival singles — steady mixed
        traffic (ragged lengths dominate the batch structure).
      - "bursty": Poisson-timed BURSTS of ``burst_size`` requests that
        arrive simultaneously with one shared prompt length per burst —
        the uniform-wave shape the dense compiled cache wins.

    ``shared_prefix_frac`` of requests join one of ``n_prefix_groups``
    cohorts whose prompts open with the group's fixed ``prefix_len``
    tokens (pass a page multiple to make whole prefix pages sharable).
    ``churn_frac`` of requests carry a ``cancel_after`` below their
    budget.
    """
    if arrival not in ("poisson", "bursty"):
        raise ValueError(f"arrival {arrival!r}: use 'poisson' or "
                         "'bursty'")
    if arrival == "bursty" and shared_prefix_frac > 0:
        # a per-request prefix bump would break the one-shared-length-
        # per-burst invariant (the dense-wave shape bursts exist for);
        # compose instead: merge_traces(bursty, poisson-with-prefixes)
        raise ValueError("bursty traces keep one prompt length per "
                         "burst; generate shared prefixes in a poisson "
                         "stream and merge_traces the two")
    rng = np.random.default_rng(seed)
    prefixes = [tuple(int(t) for t in rng.integers(
        1, vocab_size, prefix_len)) for _ in range(n_prefix_groups)]

    # arrival times first, so length/prefix draws can't perturb timing
    times: List[float] = []
    t = start
    if arrival == "poisson":
        for _ in range(n_requests):
            t += float(rng.exponential(mean_interarrival))
            times.append(t)
        burst_len = None
    else:
        burst_lens = []
        while len(times) < n_requests:
            t += float(rng.exponential(mean_interarrival * burst_size))
            n = min(burst_size, n_requests - len(times))
            times.extend([t] * n)
            burst_lens.extend(
                [int(rng.integers(prompt_len[0], prompt_len[1] + 1))] * n)
        burst_len = burst_lens

    reqs: List[Request] = []
    for i in range(n_requests):
        if burst_len is not None:
            plen = burst_len[i]
        else:
            plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        group = None
        if shared_prefix_frac > 0 and rng.random() < shared_prefix_frac:
            group = int(rng.integers(0, n_prefix_groups))
            plen = max(plen, prefix_len + 1)  # prefix + own tail
        tail = tuple(int(x) for x in rng.integers(
            1, vocab_size, plen - (prefix_len if group is not None
                                   else 0)))
        prompt = (prefixes[group] + tail) if group is not None else tail
        budget = int(rng.integers(output_len[0], output_len[1] + 1))
        cancel = None
        if churn_frac > 0 and budget > 1 and rng.random() < churn_frac:
            cancel = int(rng.integers(1, budget))
        reqs.append(Request(rid=f"{rid_prefix}{i}", arrival=times[i],
                            prompt=prompt, max_new_tokens=budget,
                            prefix_group=group, cancel_after=cancel))
    return reqs


def merge_traces(*traces: Sequence[Request]) -> List[Request]:
    """Interleave traces by arrival time (rids must already be unique —
    give each source a distinct ``rid_prefix``)."""
    out = [r for tr in traces for r in tr]
    rids = [r.rid for r in out]
    if len(set(rids)) != len(rids):
        raise ValueError("merge_traces: duplicate rids across traces "
                         "(use distinct rid_prefix per source)")
    return sorted(out, key=lambda r: (r.arrival, r.rid))


def save_trace(path: str, trace: Sequence[Request]) -> None:
    with open(path, "w") as f:
        for r in trace:
            f.write(json.dumps(r.to_json()) + "\n")


def load_trace(path: str) -> List[Request]:
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                out.append(Request.from_json(json.loads(ln)))
    return out


def trace_stats(trace: Sequence[Request]) -> dict:
    """The shape summary a bench row carries next to its numbers."""
    if not trace:
        return {"n_requests": 0}
    plens = np.asarray([len(r.prompt) for r in trace])
    budgets = np.asarray([r.max_new_tokens for r in trace])
    arr = np.asarray([r.arrival for r in trace])
    return {
        "n_requests": len(trace),
        "prompt_len_min": int(plens.min()),
        "prompt_len_max": int(plens.max()),
        "prompt_tokens": int(plens.sum()),
        "output_budget_tokens": int(budgets.sum()),
        "span": round(float(arr.max() - arr.min()), 4),
        "shared_prefix_requests": sum(
            1 for r in trace if r.prefix_group is not None),
        "churn_requests": sum(
            1 for r in trace if r.cancel_after is not None),
    }
