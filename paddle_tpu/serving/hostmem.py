"""Host-DRAM offload arena: the serving stack's third memory tier.

Capacity used to end at HBM — under pressure the paged pool compacts
to int8 (PR 14) and then *sheds*. The paper's layer map dedicates a
whole layer to exactly this gap (SURVEY §1, layer 2: host/device
allocators with pinned-host staging below the device runtime), and
production stacks (vLLM swap space, DeepSpeed-Inference/FlexGen
offload) all grow the same organ: a byte-budgeted **host-side page
store** that parks cold prefix-cache pages and preempted requests'
live chains, paged back on demand at a priced transfer cost.

``HostArena`` is the third instance of the budgeted-cache discipline
already proven twice in this codebase (``PagedKVCache``'s page pool,
``AdapterCache``'s device bank):

- **conservation census**: every budgeted byte is exactly one of
  pinned / evictable / free — ``census_ok()`` checks it, the engine
  samples it every turn, and the bench gate fails if it ever broke;
- **atomic refusal**: a ``put`` that cannot fit (even after evicting
  every evictable entry) raises ``MemoryError`` having mutated
  NOTHING, so callers can decline-and-continue safely;
- **LRU retention with pinning**: evictable entries (spilled
  prefix-cache pages) die oldest-first under pressure; pinned entries
  (a preempted request's live chain — its only copy) are never
  reclaimed until their owner unpins them.

The arena stores opaque host objects (whatever the factory's
``export_kv_pages`` returned) priced at caller-declared byte costs —
an int8-compacted page spills at its int8+scale price, the
``kv_quant_page_bytes`` arithmetic carried through the tier boundary.
The arena never touches device state and keeps no engine references;
``PagedKVCache.note_hostmem`` wires it in as the eviction spill
target, and the engine prices every page crossing on its virtual
clock (``kv_pageout`` / ``kv_pagein``, the ``adapter_upload`` /
``KVHandoff`` transfer-pricing pattern).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ..obs import ledger as obs_ledger


@dataclasses.dataclass(frozen=True)
class HostMemConfig:
    """Engine-facing knob bundle for ``ServingEngine(hostmem=...)``.

    ``byte_budget`` bounds the arena (host DRAM is big but not free —
    an unbounded swap space hides leaks and lies about capacity).
    ``page_bytes`` optionally overrides the per-page full-precision
    transfer/storage price; by default the engine derives it from the
    factory (``page_bytes_`` when advertised, else the live pool's
    measured bytes / page count)."""

    byte_budget: int
    page_bytes: Optional[int] = None

    def __post_init__(self):
        if self.byte_budget <= 0:
            raise ValueError("hostmem byte_budget must be > 0 bytes")
        if self.page_bytes is not None and self.page_bytes <= 0:
            raise ValueError("hostmem page_bytes must be > 0 bytes")


def as_hostmem_config(spec) -> Optional[HostMemConfig]:
    """None | int byte budget | HostMemConfig -> HostMemConfig."""
    if spec is None or isinstance(spec, HostMemConfig):
        return spec
    if isinstance(spec, bool):
        raise ValueError("hostmem= takes a byte budget (int) or "
                         "HostMemConfig, not a bare bool")
    if isinstance(spec, int):
        return HostMemConfig(byte_budget=spec)
    raise ValueError(f"hostmem= {spec!r}: pass None, a byte budget, "
                     "or a HostMemConfig")


class _Entry:
    __slots__ = ("data", "nbytes", "quant", "epoch", "owner")

    def __init__(self, data, nbytes: int, quant: bool, epoch: int,
                 owner: Optional[str]):
        self.data = data
        self.nbytes = nbytes
        self.quant = quant
        self.epoch = epoch
        self.owner = owner  # pin owner (preempted rid); None = LRU


class HostArena:
    """Byte-budgeted host page store with pin/LRU/census.

    Keys are opaque hashables — the paged bookkeeper keys spilled
    pages by their FULL token prefix (root..page), so a spilled
    chain's identity survives device page-id recycling, replica
    restarts, and arena-internal eviction of unrelated entries.
    """

    def __init__(self, byte_budget: int):
        if byte_budget <= 0:
            raise ValueError("HostArena byte_budget must be > 0")
        self.byte_budget = int(byte_budget)
        self.free_bytes = int(byte_budget)
        self._entries: Dict[object, _Entry] = {}
        self._lru: Dict[object, bool] = {}  # evictable; insertion=LRU
        self._stats = {"pageouts": 0, "pageins": 0, "evictions": 0,
                       "refusals": 0, "peak_bytes": 0}

    # --- state probes -----------------------------------------------------
    def __contains__(self, key) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def stored_bytes(self) -> int:
        return self.byte_budget - self.free_bytes

    def pinned_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values()
                   if e.owner is not None)

    def evictable_bytes(self) -> int:
        return sum(self._entries[k].nbytes for k in self._lru)

    def peek(self, key) -> Optional[_Entry]:
        """Non-mutating probe (no LRU refresh, no pagein counted) —
        what the bookkeeper's match path uses to price an admission
        before committing to it."""
        return self._entries.get(key)

    # --- the budgeted store -----------------------------------------------
    def put(self, key, data, nbytes: int, *, quant: bool = False,
            epoch: int = 0, pin: Optional[str] = None) -> None:
        """Store one spilled page. ATOMIC REFUSAL: if ``nbytes``
        cannot fit even after evicting every evictable entry,
        ``MemoryError`` fires having mutated nothing (the caller —
        eviction spill or preemption — declines and proceeds as if
        the arena were absent). Otherwise evictable entries die
        oldest-first until the page fits. Duplicate keys are a caller
        bug (the bookkeeper skips re-spilling a key it already holds).
        """
        nbytes = int(nbytes)
        if nbytes <= 0:
            raise ValueError("put: nbytes must be > 0")
        if key in self._entries:
            raise ValueError(f"put: key already stored: {key!r}")
        if nbytes > self.free_bytes + self.evictable_bytes():
            self._stats["refusals"] += 1
            raise MemoryError(
                f"host arena exhausted: need {nbytes} bytes, "
                f"{self.free_bytes} free + {self.evictable_bytes()} "
                f"evictable of {self.byte_budget} budget")
        while self.free_bytes < nbytes:
            self._evict_lru()
        self.free_bytes -= nbytes
        e = _Entry(data, nbytes, bool(quant), int(epoch), pin)
        self._entries[key] = e
        if pin is None:
            self._lru[key] = True
        self._stats["pageouts"] += 1
        self._stats["peak_bytes"] = max(self._stats["peak_bytes"],
                                        self.stored_bytes())

    def _evict_lru(self):
        key = next(iter(self._lru))
        del self._lru[key]
        e = self._entries.pop(key)
        self.free_bytes += e.nbytes
        self._stats["evictions"] += 1

    def take(self, key) -> _Entry:
        """Page-in: remove and return the entry (the device pool is
        about to hold the content again; keeping a second copy would
        double-count the census — a page that later re-parks simply
        re-spills). Counts one pagein."""
        e = self._entries.pop(key)
        self._lru.pop(key, None)
        self.free_bytes += e.nbytes
        self._stats["pageins"] += 1
        return e

    def drop(self, key) -> bool:
        """Forget an entry without serving it (purge after a crash,
        shed of a preempted request). Idempotent; returns whether the
        key was present."""
        e = self._entries.pop(key, None)
        if e is None:
            return False
        self._lru.pop(key, None)
        self.free_bytes += e.nbytes
        return True

    def pin(self, key, owner: str):
        """LRU -> pinned: the entry becomes ``owner``'s (a preempted
        request's live chain must outlive arbitrary spill traffic)."""
        e = self._entries[key]
        if e.owner is None:
            self._lru.pop(key, None)
        e.owner = str(owner)

    def unpin(self, key):
        """Pinned -> LRU (the owner no longer needs the guarantee —
        e.g. a preempted request restored without consuming every
        spilled page). Idempotent for already-evictable entries."""
        e = self._entries.get(key)
        if e is None or e.owner is None:
            return
        e.owner = None
        self._lru[key] = True

    def drop_owner(self, owner: str) -> int:
        """Drop every entry pinned by ``owner`` (a preempted request
        that was shed while queued: its chain will never page back
        in). Returns entries dropped."""
        keys = [k for k, e in self._entries.items()
                if e.owner == owner]
        for k in keys:
            self.drop(k)
        return len(keys)

    # --- census + stats ----------------------------------------------------
    def populations(self) -> Tuple[int, int, int]:
        """The byte-census populations (pinned, evictable, free) —
        shared between ``census_ok`` and the cost ledger's occupancy
        sampler (capacity = ``byte_budget``). Stored bytes are summed
        from the live entries, NOT derived from ``free_bytes``, so
        the balance check cross-checks the two bookkeepers."""
        pinned = sum(e.nbytes for e in self._entries.values()
                     if e.owner is not None)
        evictable = sum(e.nbytes for e in self._entries.values()
                        if e.owner is None)
        return pinned, evictable, self.free_bytes

    def owner_counts(self) -> Dict[str, int]:
        """owner -> live entry count: pinned entries under their
        preemption owner rid, plain LRU spill under ``"cache"`` — the
        attribution view the cost ledger books host-tier page-turns
        by."""
        counts: Dict[str, int] = {}
        for e in self._entries.values():
            owner = e.owner if e.owner is not None else "cache"
            counts[owner] = counts.get(owner, 0) + 1
        return counts

    def census_ok(self) -> bool:
        """The conservation invariant: pinned + evictable + free ==
        budget, every LRU key stored and unpinned, every unpinned
        entry in the LRU (arithmetic shared via
        ``obs.ledger.census_balanced``)."""
        if not obs_ledger.census_balanced(self.byte_budget,
                                          *self.populations()):
            return False
        if any(k not in self._entries or
               self._entries[k].owner is not None for k in self._lru):
            return False
        return all(e.owner is not None or k in self._lru
                   for k, e in self._entries.items())

    def stats(self) -> dict:
        pinned = sum(1 for e in self._entries.values()
                     if e.owner is not None)
        return {
            "byte_budget": self.byte_budget,
            "stored_bytes": self.stored_bytes(),
            "pinned_bytes": self.pinned_bytes(),
            "evictable_bytes": self.evictable_bytes(),
            "free_bytes": self.free_bytes,
            "entries": len(self._entries),
            "pinned_entries": pinned,
            "evictable_entries": len(self._lru),
            "pageouts": self._stats["pageouts"],
            "pageins": self._stats["pageins"],
            "evictions": self._stats["evictions"],
            "refusals": self._stats["refusals"],
            "peak_bytes": self._stats["peak_bytes"],
        }
