"""paddle.callbacks namespace (~ python/paddle/callbacks.py re-exporting
hapi callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau, VisualDL,
)
