"""hapi Model: fit/evaluate/predict/save/load + summary.

~ python/paddle/hapi/model.py:907 with the DynamicGraphAdapter (:667)
folded in, plus a StaticGraphAdapter (~ model.py:248): constructing a
Model under ``paddle.enable_static()`` builds one captured Program per
mode (train/eval/predict) from the declared InputSpecs and drives it
through the static Executor — same fit/evaluate/predict surface.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..autograd import no_grad
from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer.layers import Layer


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class StaticGraphAdapter:
    """~ hapi/model.py StaticGraphAdapter:248.

    Builds one (main, startup) Program pair per mode from the Model's
    InputSpecs: inputs/labels become ``static.data`` feed slots, the
    network + loss trace into the captured graph, and ``train`` appends
    ``optimizer.minimize``. Metrics run host-side on the fetched outputs
    (the reference fetches metric op outputs; capability-identical).
    """

    def __init__(self, model: "Model"):
        self.model = model
        self._progs = {}
        self._exe = None
        self._startup_done = set()

    def _executor(self):
        if self._exe is None:
            from ..static import Executor
            self._exe = Executor()
        return self._exe

    @staticmethod
    def _declare(specs, prefix):
        from .. import static
        out = []
        for i, s in enumerate(specs):
            shape = [(-1 if d is None else int(d)) for d in s.shape]
            out.append(static.data(s.name or f"{prefix}{i}", shape, s.dtype))
        return out

    def _build(self, mode):
        if mode in self._progs:
            return self._progs[mode]
        from ..static import Program, program_guard
        m = self.model
        if not m._input_specs:
            raise ValueError(
                "Model in static mode requires inputs=[InputSpec(...)]")
        if mode in ("train", "eval") and m._loss is not None \
                and not m._label_specs:
            raise ValueError(
                "Model prepared with a loss in static mode requires "
                "labels=[InputSpec(...)] at construction")
        main, startup = Program(), Program()
        with program_guard(main, startup):
            ins = self._declare(m._input_specs, "x")
            m.network.train() if mode == "train" else m.network.eval()
            outs = _to_list(m.network(*ins))
            feed_names = [v.name for v in ins]
            fetches = list(outs)
            if mode in ("train", "eval") and m._loss is not None \
                    and m._label_specs:
                lbls = self._declare(m._label_specs, "label")
                feed_names += [v.name for v in lbls]
                loss = m._loss(*(outs + lbls))
                fetches = [loss] + fetches
                if mode == "train":
                    m._optimizer.minimize(loss)
        self._progs[mode] = (main, startup, feed_names, fetches)
        return self._progs[mode]

    def _run(self, mode, inputs, labels):
        main, startup, feed_names, fetches = self._build(mode)
        exe = self._executor()
        if mode not in self._startup_done:
            exe.run(startup)
            self._startup_done.add(mode)
        vals = list(inputs) + list(labels)
        feed = {n: (v.numpy() if hasattr(v, "numpy") else np.asarray(v))
                for n, v in zip(feed_names, vals)}
        return exe.run(main, feed=feed, fetch_list=fetches)

    def _host_metrics(self, outs_np, labels):
        m = self.model
        metrics = []
        outs = [Tensor(o) for o in outs_np]
        lbls = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
                for x in labels]
        for mt in m._metrics:
            mt.update(*_to_list(mt.compute(*(outs + lbls))))
            metrics.append(mt.accumulate())
        return metrics

    def train_batch(self, inputs, labels):
        res = self._run("train", inputs, labels)
        loss, outs = res[0], res[1:]
        metrics = self._host_metrics(outs, labels)
        return ([float(loss)], metrics) if metrics else [float(loss)]

    def eval_batch(self, inputs, labels):
        has_loss = self.model._loss is not None and labels
        res = self._run("eval", inputs, labels)
        if has_loss:
            loss, outs = res[0], res[1:]
        else:
            loss, outs = None, res
        metrics = self._host_metrics(outs, labels)
        if loss is not None:
            return [float(loss)], metrics
        return metrics

    def predict_batch(self, inputs):
        res = self._run("predict", inputs, [])
        return res[0] if len(res) == 1 else res


class Model:
    """~ hapi/model.py Model:907."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._input_specs = _to_list(inputs)
        self._label_specs = _to_list(labels)
        # adapter chosen at construction time, like the reference (model.py
        # picks by in_dynamic_mode() when Model is created)
        from ..static import in_static_mode
        self._adapter = StaticGraphAdapter(self) if in_static_mode() \
            else None

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        return self

    # -- single-batch ops ---------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        if self._adapter is not None:
            return self._adapter.train_batch(_to_list(inputs),
                                             _to_list(labels))
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self.network(*inputs)
        losses = self._loss(*(_to_list(outputs) + labels))
        losses.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(*_to_list(m.compute(*( _to_list(outputs) + labels))))
            metrics.append(m.accumulate())
        return ([float(losses._value)], metrics) if metrics \
            else [float(losses._value)]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        if self._adapter is not None:
            return self._adapter.eval_batch(_to_list(inputs),
                                            _to_list(labels))
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = self.network(*inputs)
        metrics = []
        loss_v = None
        if self._loss is not None and labels:
            loss_v = [float(self._loss(*(_to_list(outputs) + labels))._value)]
        for m in self._metrics:
            m.update(*_to_list(m.compute(*( _to_list(outputs) + labels))))
            metrics.append(m.accumulate())
        return (loss_v, metrics) if loss_v is not None else metrics

    @no_grad()
    def predict_batch(self, inputs):
        if self._adapter is not None:
            return self._adapter.predict_batch(_to_list(inputs))
        self.network.eval()
        return self.network(*_to_list(inputs))

    # -- loops --------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """~ model.py fit:1557."""
        from .callbacks import CallbackList, LRScheduler, ProgBarLogger

        train_loader = train_data if isinstance(train_data, DataLoader) \
            else DataLoader(train_data, batch_size=batch_size,
                            shuffle=shuffle, drop_last=drop_last,
                            num_workers=num_workers)
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) \
                else DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)

        cbs = _to_list(callbacks)
        if verbose:
            cbs = [ProgBarLogger(log_freq, verbose=verbose)] + cbs
        cbs.append(LRScheduler())
        cb = CallbackList(cbs)
        cb.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cb.set_params({"epochs": epochs, "steps": steps, "verbose": verbose})

        self.stop_training = False
        cb.on_train_begin()
        it_count = 0
        for epoch in range(epochs):
            cb.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, data in enumerate(train_loader):
                cb.on_train_batch_begin(step)
                inputs, labels = self._split_data(data)
                res = self.train_batch(inputs, labels)
                logs = self._pack_logs(res)
                cb.on_train_batch_end(step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    self.stop_training = True
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _callbacks=cb)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cb.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training:
                break
        cb.on_train_end()
        if save_dir:
            self.save(os.path.join(save_dir, "final"))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _callbacks=None):
        loader = eval_data if isinstance(eval_data, DataLoader) \
            else DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        cb = _callbacks
        if cb:
            cb.on_eval_begin()
        losses = []
        for data in loader:
            inputs, labels = self._split_data(data)
            res = self.eval_batch(inputs, labels)
            if isinstance(res, tuple):
                losses.append(res[0][0])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        if cb:
            cb.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = test_data if isinstance(test_data, DataLoader) \
            else DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers)
        outputs = []
        for data in loader:
            inputs, _ = self._split_data(data)
            out = self.predict_batch(inputs)
            outputs.append(out.numpy() if isinstance(out, Tensor) else out)
        if stack_outputs:
            return [np.concatenate(outputs)]
        return [outputs]

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as _save
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load
        self.network.set_state_dict(_load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self):
        return self.network.parameters()

    # -- helpers ------------------------------------------------------------
    def _split_data(self, data, has_labels=True):
        if isinstance(data, (list, tuple)):
            data = list(data)
            if has_labels and len(data) >= 2:
                return data[:-1], data[-1:]
            return data, []
        return [data], []

    def _pack_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            loss, metrics = res
            logs["loss"] = loss[0]
            for m, v in zip(self._metrics, metrics):
                logs[m.name()] = v
        else:
            logs["loss"] = res[0]
        return logs

    def summary(self, input_size=None, dtype=None):
        return summary_layer(self.network)


def summary_layer(network: Layer):
    """~ hapi/model_summary.py — parameter count table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in network.named_parameters():
        n = p.size
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Param':{width}s} {'Shape':24s} {'Count':>12s}"]
    for name, shape, n in rows:
        lines.append(f"{name:{width}s} {str(shape):24s} {n:12d}")
    lines.append("-" * (width + 38))
    lines.append(f"Total params: {total:,} (trainable {trainable:,})")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def summary(net, input_size=None, dtypes=None):
    return summary_layer(net)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """~ paddle.flops (python/paddle/hapi/dynamic_flops.py).

    Forward-hook FLOPs counter: runs one forward pass on zeros of
    ``input_size`` capturing per-layer in/out shapes, then applies the
    standard per-layer-type cost formulas (multiply-adds counted as 2 ops
    halved, matching the reference's convention of counting MACs).
    """
    import numpy as np
    from ..core.tensor import Tensor
    from ..nn import layer as _nl
    from ..autograd import no_grad

    counts = {}
    handles = []

    def make_hook(name, lyr):
        def hook(layer, inputs, outputs):
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            f = 0
            tname = type(layer).__name__
            if custom_ops and type(layer) in custom_ops:
                f = custom_ops[type(layer)](layer, x, out)
            elif hasattr(layer, "weight") and layer.weight is not None:
                w = layer.weight
                if "Conv" in tname:
                    out_elems = int(np.prod(out.shape))
                    kernel_ops = int(np.prod(w.shape[1:]))
                    f = out_elems * kernel_ops
                elif "Linear" in tname:
                    batch = int(np.prod(x.shape[:-1]))
                    f = batch * int(np.prod(w.shape))
                elif "Norm" in tname:
                    f = int(np.prod(x.shape)) * 2
                elif "Embedding" in tname:
                    f = 0
            elif "Pool" in tname:
                f = int(np.prod(out.shape))
            if f:
                counts[name] = counts.get(name, 0) + f
        return hook

    for name, lyr in net.named_sublayers(include_self=True):
        handles.append(lyr.register_forward_post_hook(make_hook(name or "net", lyr)))
    try:
        x = Tensor(np.zeros(tuple(input_size), dtype="float32"))
        was_training = net.training
        net.eval()
        with no_grad():
            net(x)
        net.training = was_training
    finally:
        for h in handles:
            h.remove()
    total = sum(counts.values())
    if print_detail:
        for k, v in counts.items():
            print(f"{k:40s} {v:15,d}")
        print(f"Total FLOPs: {total:,}")
    return total
