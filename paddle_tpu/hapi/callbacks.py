"""Training callbacks. ~ python/paddle/hapi/callbacks.py."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    """~ callbacks.py Callback:118."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """~ callbacks.py ProgBarLogger:287 (text progress per epoch)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            print(f"step {step + 1}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {items}")


def _fmt(v):
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(f"{float(x):.4f}" for x in np.ravel(v)) + "]"
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    """~ callbacks.py ModelCheckpoint:533."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """~ callbacks.py LRSchedulerCallback:598."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    """~ callbacks.py EarlyStopping:689."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"early stopping (best {self.monitor}="
                          f"{self.best:.5f})")


class VisualDL(Callback):
    """Metric logger writing jsonl (the in-core VisualDL writer slot,
    callbacks.py:843; the visualization frontend is external)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = open(os.path.join(log_dir, "metrics.jsonl"), "a")
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        self._step += 1
        rec = {"step": self._step}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(np.ravel(v)[0])
            except (TypeError, ValueError):
                pass
        self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        self._f.close()


class ReduceLROnPlateau(Callback):
    """~ hapi/callbacks.py ReduceLROnPlateau: shrink LR when the monitored
    metric stops improving."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.mode = mode
        self._best = None
        self._wait = 0
        self._cooldown_counter = 0

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == "max" or (self.mode == "auto"
                                  and "acc" in self.monitor):
            return cur > self._best + self.min_delta
        return cur < self._best - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._cooldown_counter > 0:
            self._cooldown_counter -= 1
            self._wait = 0
        if self._better(cur):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {lr:.3e}")
            self._cooldown_counter = self.cooldown
            self._wait = 0
