"""High-level Model API (hapi).

~ python/paddle/hapi/model.py:907 (Model.fit:1557/evaluate/predict) and
callbacks.py (ModelCheckpoint:533, EarlyStopping:689, LRScheduler:598).
Single dynamic-graph adapter (the static adapter has no TPU analog — jit
happens under the hood per-step when enabled).
"""
from .model import Model, summary  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
)
