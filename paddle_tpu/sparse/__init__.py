"""paddle_tpu.sparse — COO/CSR sparse tensors.

~ python/paddle/sparse/ over phi sparse kernels (phi/core/sparse_coo_tensor.h,
phi/kernels/sparse/). TPU reality: XLA has no sparse formats; the idiomatic
mapping keeps COO/CSR as index+value pairs with dense compute via
scatter/gather (segment_sum) which XLA lowers well for moderate sparsity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class SparseCooTensor(Tensor):
    """COO: indices (ndim, nnz) + values (nnz, ...)."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        self.indices_ = indices if isinstance(indices, Tensor) \
            else Tensor(jnp.asarray(indices))
        self.values_ = values if isinstance(values, Tensor) \
            else Tensor(jnp.asarray(values))
        self.dense_shape = list(int(s) for s in shape)
        super().__init__(self._to_dense_value(), stop_gradient=stop_gradient)

    def _to_dense_value(self):
        idx = tuple(self.indices_._value)
        dense = jnp.zeros(self.dense_shape, self.values_._value.dtype)
        return dense.at[idx].add(self.values_._value)

    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._to_dense_value(),
                      stop_gradient=self.stop_gradient)

    @property
    def nnz(self):
        return self.values_.shape[0]


class SparseCsrTensor(Tensor):
    def __init__(self, crows, cols, values, shape, stop_gradient=True):
        self.crows_ = Tensor(jnp.asarray(
            crows._value if isinstance(crows, Tensor) else crows))
        self.cols_ = Tensor(jnp.asarray(
            cols._value if isinstance(cols, Tensor) else cols))
        self.values_ = Tensor(jnp.asarray(
            values._value if isinstance(values, Tensor) else values))
        self.dense_shape = list(int(s) for s in shape)
        super().__init__(self._to_dense_value(), stop_gradient=stop_gradient)

    def _to_dense_value(self):
        crows = np.asarray(self.crows_._value)
        cols = self.cols_._value
        vals = self.values_._value
        nrows = self.dense_shape[0]
        row_idx = np.repeat(np.arange(nrows), np.diff(crows))
        dense = jnp.zeros(self.dense_shape, vals.dtype)
        return dense.at[jnp.asarray(row_idx), cols].add(vals)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._to_dense_value())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices._value if isinstance(indices, Tensor)
                         else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape, stop_gradient)


def matmul(x, y):
    from ..ops.linalg import matmul as dense_matmul
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    return dense_matmul(xd, yd)


def relu(x):
    if isinstance(x, SparseCooTensor):
        from ..ops.activation import relu as dense_relu
        return SparseCooTensor(x.indices_, dense_relu(x.values_),
                               x.dense_shape)
    from ..ops.activation import relu as dense_relu
    return dense_relu(x)


def _coo_from_dense(dense, stop_gradient=True):
    """Host-side sparsification (data-dependent nnz -> eager op, like the
    reference's sparse kernels which also materialize index sets)."""
    arr = np.asarray(dense._value if isinstance(dense, Tensor) else dense)
    # last dim is channels for conv-style layouts: a site is occupied if any
    # channel is nonzero
    occ = np.abs(arr).sum(axis=-1) if arr.ndim > 1 else np.abs(arr)
    coords = np.argwhere(occ != 0)
    vals = arr[tuple(coords.T)]
    return SparseCooTensor(coords.T.astype(np.int64), vals, arr.shape)


class ReLU:
    """~ paddle.sparse.ReLU (phi/kernels/sparse/activation_kernel.cc):
    elementwise on stored values only — the sparsity pattern is preserved."""

    def __call__(self, x):
        return relu(x)


class Conv3D:
    """~ paddle.sparse.Conv3D (phi/kernels/sparse/convolution_kernel.h).

    NDHWC sparse conv: computed as a dense lax conv (XLA/MXU path) and
    re-sparsified to the reachable output sites. The reference's gather-
    scatter rulebook formulation targets GPU hash tables; on TPU the dense
    formulation wins until occupancy is very low, at which point the Pallas
    gather kernel applies."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..core.generator import default_generator
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.kernel_size = ks
        self.stride = (stride,) * 3 if isinstance(stride, int) \
            else tuple(stride)
        self.padding = (padding,) * 3 if isinstance(padding, int) \
            else tuple(padding)
        self.dilation = (dilation,) * 3 if isinstance(dilation, int) \
            else tuple(dilation)
        self.groups = groups
        fan_in = in_channels * int(np.prod(ks))
        limit = float(np.sqrt(6.0 / max(1, fan_in)))
        from ..core.tensor import Parameter
        key = default_generator().next_key()
        self.weight = Parameter(jax.random.uniform(
            key, ks + (in_channels // groups, out_channels),
            jnp.float32, -limit, limit))
        self.bias = Parameter(jnp.zeros((out_channels,))) \
            if bias_attr is not False else None
        self._subm = False

    def _dense_conv(self, dense):
        dn = jax.lax.conv_dimension_numbers(
            dense.shape, self.weight._value.shape,
            ("NDHWC", "DHWIO", "NDHWC"))
        out = jax.lax.conv_general_dilated(
            dense, self.weight._value, self.stride,
            [(p, p) for p in self.padding], rhs_dilation=self.dilation,
            dimension_numbers=dn, feature_group_count=self.groups)
        if self.bias is not None:
            out = out + self.bias._value
        return out

    def __call__(self, x):
        dense = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        out = self._dense_conv(dense)
        if self._subm:
            # submanifold: output keeps the input's sparsity pattern
            idx = x.indices_._value  # (4, nnz) over (n, d, h, w) sites
            vals = out[tuple(idx)]   # (nnz, C_out)
            return SparseCooTensor(idx, vals, list(out.shape))
        return _coo_from_dense(Tensor(out))


class SubmConv3D(Conv3D):
    """~ paddle.sparse.SubmConv3D — submanifold conv (output sites = input
    sites), the standard trick keeping 3D point-cloud activations sparse."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._subm = True


class BatchNorm:
    """~ paddle.sparse.BatchNorm — batch norm over stored values (channel
    stats computed on the nnz values only, matching the reference)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    def train(self):
        self._bn.train()

    def eval(self):
        self._bn.eval()

    def __call__(self, x):
        if isinstance(x, SparseCooTensor):
            vals = self._bn(x.values_)
            return SparseCooTensor(x.indices_, vals, x.dense_shape)
        return self._bn(x)


class MaxPool3D:
    """~ paddle.sparse.MaxPool3D — NDHWC max pool on the dense view,
    re-sparsified."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def __call__(self, x):
        from ..nn import functional as F
        dense = Tensor(x._value if isinstance(x, Tensor) else jnp.asarray(x))
        out = F.max_pool3d(dense, self.kernel_size, self.stride, self.padding,
                           data_format="NDHWC")
        return _coo_from_dense(out)


def add(x, y):
    """~ paddle.sparse.add — union-pattern elementwise add."""
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    from ..ops.math import add as dense_add
    return _coo_from_dense(dense_add(xd, yd))


def masked_matmul(x, y, mask):
    """~ paddle.sparse.masked_matmul: dense@dense evaluated only at mask's
    sparsity pattern (SDDMM). TPU lowering: full MXU matmul + gather at the
    pattern — wins whenever nnz is a significant fraction of the output."""
    from ..ops.linalg import matmul as dense_matmul
    out = dense_matmul(x, y)
    if isinstance(mask, SparseCsrTensor):
        crows = np.asarray(mask.crows_._value)
        cols = np.asarray(mask.cols_._value)
        rows = np.repeat(np.arange(mask.dense_shape[0]), np.diff(crows))
        vals = out._value[rows, cols]
        return SparseCsrTensor(crows, cols, vals, mask.dense_shape)
    idx = mask.indices_._value
    vals = out._value[tuple(idx)]
    return SparseCooTensor(idx, vals, mask.dense_shape)
