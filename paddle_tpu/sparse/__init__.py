"""paddle_tpu.sparse — COO/CSR sparse tensors.

~ python/paddle/sparse/ over phi sparse kernels (phi/core/sparse_coo_tensor.h,
phi/kernels/sparse/). TPU reality: XLA has no sparse formats; the idiomatic
mapping keeps COO/CSR as index+value pairs with dense compute via
scatter/gather (segment_sum) which XLA lowers well for moderate sparsity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class SparseCooTensor(Tensor):
    """COO: indices (ndim, nnz) + values (nnz, ...)."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        self.indices_ = indices if isinstance(indices, Tensor) \
            else Tensor(jnp.asarray(indices))
        self.values_ = values if isinstance(values, Tensor) \
            else Tensor(jnp.asarray(values))
        self.dense_shape = list(int(s) for s in shape)
        super().__init__(self._to_dense_value(), stop_gradient=stop_gradient)

    def _to_dense_value(self):
        idx = tuple(self.indices_._value)
        dense = jnp.zeros(self.dense_shape, self.values_._value.dtype)
        return dense.at[idx].add(self.values_._value)

    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._to_dense_value(),
                      stop_gradient=self.stop_gradient)

    @property
    def nnz(self):
        return self.values_.shape[0]


class SparseCsrTensor(Tensor):
    def __init__(self, crows, cols, values, shape, stop_gradient=True):
        self.crows_ = Tensor(jnp.asarray(
            crows._value if isinstance(crows, Tensor) else crows))
        self.cols_ = Tensor(jnp.asarray(
            cols._value if isinstance(cols, Tensor) else cols))
        self.values_ = Tensor(jnp.asarray(
            values._value if isinstance(values, Tensor) else values))
        self.dense_shape = list(int(s) for s in shape)
        super().__init__(self._to_dense_value(), stop_gradient=stop_gradient)

    def _to_dense_value(self):
        crows = np.asarray(self.crows_._value)
        cols = self.cols_._value
        vals = self.values_._value
        nrows = self.dense_shape[0]
        row_idx = np.repeat(np.arange(nrows), np.diff(crows))
        dense = jnp.zeros(self.dense_shape, vals.dtype)
        return dense.at[jnp.asarray(row_idx), cols].add(vals)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._to_dense_value())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices._value if isinstance(indices, Tensor)
                         else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape, stop_gradient)


def _coo_rows_cols(x):
    """(rows, cols, vals) jnp arrays for a 2-D sparse tensor."""
    if isinstance(x, SparseCooTensor):
        idx = x.indices_._value
        return idx[0], idx[1], x.values_._value
    # CSR: expand crows to per-nnz row ids (host side — crows is static)
    crows = np.asarray(x.crows_._value)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return jnp.asarray(rows), x.cols_._value, x.values_._value


def matmul(x, y):
    """sparse @ dense without densifying x: gather rows of y by col index
    and segment-sum into output rows (~ phi/kernels/sparse/matmul_kernel;
    the scatter-add formulation XLA lowers to MXU-friendly gathers)."""
    from ..ops.linalg import matmul as dense_matmul
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        rows, cols, vals = _coo_rows_cols(x)
        M = x.dense_shape[0]
        contrib = vals[:, None] * yv[cols]          # (nnz, N)
        out = jax.ops.segment_sum(contrib, rows, num_segments=M)
        return Tensor(out.astype(yv.dtype))
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        # dense @ sparse == (sparse^T @ dense^T)^T using the same kernel
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        rows, cols, vals = _coo_rows_cols(y)
        N = y.dense_shape[1]
        contrib = vals[:, None] * xv.T[rows]        # (nnz, M)
        out = jax.ops.segment_sum(contrib, cols, num_segments=N)
        return Tensor(out.T.astype(xv.dtype))
    return dense_matmul(x, y)


def masked_matmul(x, y, mask):
    """~ paddle.sparse.masked_matmul: dense @ dense sampled at `mask`'s
    sparsity pattern — out.values[n] = x[i_n] . y[:, j_n]; never builds
    the dense product."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    rows, cols, _ = _coo_rows_cols(mask)
    vals = jnp.einsum("nk,nk->n", xv[rows], yv.T[cols])
    out_shape = [xv.shape[0], yv.shape[1]]
    return SparseCooTensor(jnp.stack([rows, cols]), vals, out_shape)


def _coalesce_arrays(idx, vals, shape):
    """Sum duplicate coordinates; returns sorted unique (idx, vals)."""
    idx_np = np.asarray(idx)
    lin = np.ravel_multi_index(tuple(idx_np), tuple(shape))
    uniq, inv = np.unique(lin, return_inverse=True)
    summed = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(inv),
                                 num_segments=len(uniq))
    coords = np.stack(np.unravel_index(uniq, tuple(shape)))
    return jnp.asarray(coords), summed


def coalesce(x: "SparseCooTensor") -> "SparseCooTensor":
    """~ phi sparse coalesce kernel: merge duplicate indices."""
    idx, vals = _coalesce_arrays(x.indices_._value, x.values_._value,
                                 x.dense_shape)
    return SparseCooTensor(idx, vals, x.dense_shape)


def add(x, y):
    """sparse + sparse (same shape): concatenate and coalesce — index/value
    compute only (~ phi/kernels/sparse/elementwise_kernel)."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        idx = jnp.concatenate([x.indices_._value, y.indices_._value], axis=1)
        vals = jnp.concatenate([x.values_._value, y.values_._value])
        cidx, cvals = _coalesce_arrays(idx, vals, x.dense_shape)
        return SparseCooTensor(cidx, cvals, x.dense_shape)
    from ..ops.math import add as dense_add
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    return dense_add(xd, yd)


def multiply(x, y):
    """Elementwise multiply; sparse*dense keeps x's pattern (gather)."""
    if isinstance(x, SparseCooTensor) and not isinstance(
            y, (SparseCooTensor, SparseCsrTensor)):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        gathered = yv[tuple(x.indices_._value)]
        return SparseCooTensor(x.indices_, x.values_._value * gathered,
                               x.dense_shape)
    from ..ops.math import multiply as dense_mul
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    return dense_mul(xd, yd)


def transpose(x: "SparseCooTensor", perm):
    """Permute sparse dims by reordering the index rows."""
    idx = x.indices_._value[jnp.asarray(perm)]
    shape = [x.dense_shape[p] for p in perm]
    return coalesce(SparseCooTensor(idx, x.values_._value, shape))


def sparse_csr_to_coo(x: "SparseCsrTensor") -> "SparseCooTensor":
    rows, cols, vals = _coo_rows_cols(x)
    return SparseCooTensor(jnp.stack([rows, cols]), vals, x.dense_shape)


def sparse_coo_to_csr(x: "SparseCooTensor") -> "SparseCsrTensor":
    idx = np.asarray(x.indices_._value)
    order = np.lexsort((idx[1], idx[0]))
    rows, cols = idx[0][order], idx[1][order]
    vals = x.values_._value[jnp.asarray(order)]
    crows = np.zeros(x.dense_shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, jnp.asarray(cols), vals, x.dense_shape)


def relu(x):
    if isinstance(x, SparseCooTensor):
        from ..ops.activation import relu as dense_relu
        return SparseCooTensor(x.indices_, dense_relu(x.values_),
                               x.dense_shape)
    from ..ops.activation import relu as dense_relu
    return dense_relu(x)


def _coo_from_dense(dense, stop_gradient=True):
    """Host-side sparsification (data-dependent nnz -> eager op, like the
    reference's sparse kernels which also materialize index sets)."""
    arr = np.asarray(dense._value if isinstance(dense, Tensor) else dense)
    # last dim is channels for conv-style layouts: a site is occupied if any
    # channel is nonzero
    occ = np.abs(arr).sum(axis=-1) if arr.ndim > 1 else np.abs(arr)
    coords = np.argwhere(occ != 0)
    vals = arr[tuple(coords.T)]
    return SparseCooTensor(coords.T.astype(np.int64), vals, arr.shape)


class ReLU:
    """~ paddle.sparse.ReLU (phi/kernels/sparse/activation_kernel.cc):
    elementwise on stored values only — the sparsity pattern is preserved."""

    def __call__(self, x):
        return relu(x)


class Conv3D:
    """~ paddle.sparse.Conv3D (phi/kernels/sparse/convolution_kernel.h).

    NDHWC sparse conv: computed as a dense lax conv (XLA/MXU path) and
    re-sparsified to the reachable output sites. The reference's gather-
    scatter rulebook formulation targets GPU hash tables; on TPU the dense
    formulation wins until occupancy is very low, at which point the Pallas
    gather kernel applies."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..core.generator import default_generator
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.kernel_size = ks
        self.stride = (stride,) * 3 if isinstance(stride, int) \
            else tuple(stride)
        self.padding = (padding,) * 3 if isinstance(padding, int) \
            else tuple(padding)
        self.dilation = (dilation,) * 3 if isinstance(dilation, int) \
            else tuple(dilation)
        self.groups = groups
        fan_in = in_channels * int(np.prod(ks))
        limit = float(np.sqrt(6.0 / max(1, fan_in)))
        from ..core.tensor import Parameter
        key = default_generator().next_key()
        self.weight = Parameter(jax.random.uniform(
            key, ks + (in_channels // groups, out_channels),
            jnp.float32, -limit, limit))
        self.bias = Parameter(jnp.zeros((out_channels,))) \
            if bias_attr is not False else None
        self._subm = False

    def _dense_conv(self, dense):
        dn = jax.lax.conv_dimension_numbers(
            dense.shape, self.weight._value.shape,
            ("NDHWC", "DHWIO", "NDHWC"))
        out = jax.lax.conv_general_dilated(
            dense, self.weight._value, self.stride,
            [(p, p) for p in self.padding], rhs_dilation=self.dilation,
            dimension_numbers=dn, feature_group_count=self.groups)
        if self.bias is not None:
            out = out + self.bias._value
        return out

    def __call__(self, x):
        dense = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        out = self._dense_conv(dense)
        if self._subm:
            # submanifold: output keeps the input's sparsity pattern
            idx = x.indices_._value  # (4, nnz) over (n, d, h, w) sites
            vals = out[tuple(idx)]   # (nnz, C_out)
            return SparseCooTensor(idx, vals, list(out.shape))
        return _coo_from_dense(Tensor(out))


class SubmConv3D(Conv3D):
    """~ paddle.sparse.SubmConv3D — submanifold conv (output sites = input
    sites), the standard trick keeping 3D point-cloud activations sparse."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._subm = True


class BatchNorm:
    """~ paddle.sparse.BatchNorm — batch norm over stored values (channel
    stats computed on the nnz values only, matching the reference)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    def train(self):
        self._bn.train()

    def eval(self):
        self._bn.eval()

    def __call__(self, x):
        if isinstance(x, SparseCooTensor):
            vals = self._bn(x.values_)
            return SparseCooTensor(x.indices_, vals, x.dense_shape)
        return self._bn(x)


class MaxPool3D:
    """~ paddle.sparse.MaxPool3D — NDHWC max pool on the dense view,
    re-sparsified."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def __call__(self, x):
        from ..nn import functional as F
        dense = Tensor(x._value if isinstance(x, Tensor) else jnp.asarray(x))
        out = F.max_pool3d(dense, self.kernel_size, self.stride, self.padding,
                           data_format="NDHWC")
        return _coo_from_dense(out)


def add(x, y):
    """~ paddle.sparse.add — union-pattern elementwise add."""
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    from ..ops.math import add as dense_add
    return _coo_from_dense(dense_add(xd, yd))


def masked_matmul(x, y, mask):
    """~ paddle.sparse.masked_matmul: dense@dense evaluated only at mask's
    sparsity pattern (SDDMM). TPU lowering: full MXU matmul + gather at the
    pattern — wins whenever nnz is a significant fraction of the output."""
    from ..ops.linalg import matmul as dense_matmul
    out = dense_matmul(x, y)
    if isinstance(mask, SparseCsrTensor):
        crows = np.asarray(mask.crows_._value)
        cols = np.asarray(mask.cols_._value)
        rows = np.repeat(np.arange(mask.dense_shape[0]), np.diff(crows))
        vals = out._value[rows, cols]
        return SparseCsrTensor(crows, cols, vals, mask.dense_shape)
    idx = mask.indices_._value
    vals = out._value[tuple(idx)]
    return SparseCooTensor(idx, vals, mask.dense_shape)
