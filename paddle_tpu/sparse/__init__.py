"""paddle_tpu.sparse — COO/CSR sparse tensors.

~ python/paddle/sparse/ over phi sparse kernels (phi/core/sparse_coo_tensor.h,
phi/kernels/sparse/). TPU reality: XLA has no sparse formats; the idiomatic
mapping keeps COO/CSR as index+value pairs with dense compute via
scatter/gather (segment_sum) which XLA lowers well for moderate sparsity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class SparseCooTensor(Tensor):
    """COO: indices (ndim, nnz) + values (nnz, ...)."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        self.indices_ = indices if isinstance(indices, Tensor) \
            else Tensor(jnp.asarray(indices))
        self.values_ = values if isinstance(values, Tensor) \
            else Tensor(jnp.asarray(values))
        self.dense_shape = list(int(s) for s in shape)
        super().__init__(self._to_dense_value(), stop_gradient=stop_gradient)

    def _to_dense_value(self):
        idx = tuple(self.indices_._value)
        dense = jnp.zeros(self.dense_shape, self.values_._value.dtype)
        return dense.at[idx].add(self.values_._value)

    def indices(self):
        return self.indices_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._to_dense_value(),
                      stop_gradient=self.stop_gradient)

    @property
    def nnz(self):
        return self.values_.shape[0]


class SparseCsrTensor(Tensor):
    def __init__(self, crows, cols, values, shape, stop_gradient=True):
        self.crows_ = Tensor(jnp.asarray(
            crows._value if isinstance(crows, Tensor) else crows))
        self.cols_ = Tensor(jnp.asarray(
            cols._value if isinstance(cols, Tensor) else cols))
        self.values_ = Tensor(jnp.asarray(
            values._value if isinstance(values, Tensor) else values))
        self.dense_shape = list(int(s) for s in shape)
        super().__init__(self._to_dense_value(), stop_gradient=stop_gradient)

    def _to_dense_value(self):
        crows = np.asarray(self.crows_._value)
        cols = self.cols_._value
        vals = self.values_._value
        nrows = self.dense_shape[0]
        row_idx = np.repeat(np.arange(nrows), np.diff(crows))
        dense = jnp.zeros(self.dense_shape, vals.dtype)
        return dense.at[jnp.asarray(row_idx), cols].add(vals)

    def crows(self):
        return self.crows_

    def cols(self):
        return self.cols_

    def values(self):
        return self.values_

    def to_dense(self):
        return Tensor(self._to_dense_value())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices._value if isinstance(indices, Tensor)
                         else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape, stop_gradient)


def matmul(x, y):
    from ..ops.linalg import matmul as dense_matmul
    xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
    return dense_matmul(xd, yd)


def relu(x):
    if isinstance(x, SparseCooTensor):
        from ..ops.activation import relu as dense_relu
        return SparseCooTensor(x.indices_, dense_relu(x.values_),
                               x.dense_shape)
    from ..ops.activation import relu as dense_relu
    return dense_relu(x)
