"""paddle_tpu: a TPU-native deep-learning framework.

Brand-new design with the capabilities of the PaddlePaddle reference
(structural analysis in SURVEY.md): eager define-by-run tensors with a
`to_static` JIT path, a jax/XLA-lowered op layer, nn/optimizer/amp/io
training APIs, and mesh-based 4D+ hybrid parallelism over XLA collectives.
"""
from __future__ import annotations

from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8,
    finfo, iinfo,
)
from .core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)
from .core import flags as _flags  # noqa: F401
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.generator import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .autograd import grad, no_grad  # noqa: F401
from .autograd.tape import enable_grad  # noqa: F401

# op namespace: paddle.add / paddle.matmul / ...
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation
from .ops.creation import (  # noqa: F401
    arange, assign, bernoulli, empty, empty_like, eye, full, full_like,
    linspace, logspace, meshgrid, multinomial, normal, ones, ones_like, rand,
    randint, randn, randperm, uniform, zeros, zeros_like,
)

from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import distributed  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import obs  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import vision  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import sparse  # noqa: F401
from . import static  # noqa: F401
from .framework.io import load, save  # noqa: F401
from .framework import device  # noqa: F401

import paddle_tpu.tensor as tensor  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import linalg  # noqa: F401
from . import text  # noqa: F401
from . import hapi  # noqa: F401
from . import distribution  # noqa: F401
from . import quantization  # noqa: F401
from . import models  # noqa: F401
from . import parallel  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from .ops.control_flow import case, cond, scan, switch_case, while_loop  # noqa: F401
from .autograd.py_layer import PyLayer, PyLayerContext  # noqa: F401
from .nn.initializer import ParamAttr  # noqa: F401

from .core.place import (  # noqa: F401
    CUDAPinnedPlace, CUDAPlace, NPUPlace, XPUPlace,
)
from .distributed.parallel import DataParallel  # noqa: F401
from .ops.manipulation import slice_ as slice  # noqa: F401,A001
from .hapi.model import flops  # noqa: F401
from .core.generator import (  # noqa: F401
    get_rng_state as get_cuda_rng_state,
    set_rng_state as set_cuda_rng_state,
)

# dtype aliases completing the public dtype namespace
import builtins
import numpy as _np
bool = bool_  # noqa: A001
dtype = _np.dtype

__version__ = "0.1.0"


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """~ paddle.set_printoptions — numpy repr drives Tensor printing here."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


class set_grad_enabled:
    """~ paddle.set_grad_enabled — context manager / immediate switch."""

    def __init__(self, mode: builtins.bool):
        from .autograd import tape as _t
        self._prev = _t._set_grad_enabled(builtins.bool(mode))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        from .autograd import tape as _t
        _t._set_grad_enabled(self._prev)
        return False


def disable_signal_handler():
    """~ paddle.disable_signal_handler — the reference unhooks its C++ signal
    handlers; this runtime installs none, so this is a checked no-op."""
    return None


def check_shape(shape):
    """Validate a shape argument (list/tuple of ints, -1 allowed once)."""
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = list(shape)
    if sum(1 for s in shape if int(s) == -1) > 1:
        raise ValueError(f"shape may contain at most one -1, got {shape}")
    return shape


def batch(reader, batch_size, drop_last=False):
    """~ paddle.batch (python/paddle/batch.py) — legacy reader batching."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def is_grad_enabled() -> builtins.bool:
    from .autograd.tape import grad_enabled
    return grad_enabled()


def disable_static(place=None):
    from .static.graph import disable_static as _ds
    return _ds(place)


def enable_static():
    from .static.graph import enable_static as _es
    return _es()


def in_dynamic_mode() -> bool:
    from .static.graph import in_static_mode
    return not in_static_mode()
from . import callbacks  # noqa: F401
from . import regularizer  # noqa: F401
from . import onnx  # noqa: F401
from .framework.device import (  # noqa: F401
    is_compiled_with_cinn, is_compiled_with_cuda, is_compiled_with_ipu,
    is_compiled_with_mlu, is_compiled_with_npu, is_compiled_with_rocm,
    is_compiled_with_xpu, get_cudnn_version,
)
