"""paddle_tpu: a TPU-native deep-learning framework.

Brand-new design with the capabilities of the PaddlePaddle reference
(structural analysis in SURVEY.md): eager define-by-run tensors with a
`to_static` JIT path, a jax/XLA-lowered op layer, nn/optimizer/amp/io
training APIs, and mesh-based 4D+ hybrid parallelism over XLA collectives.
"""
from __future__ import annotations

from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    get_default_dtype, int8, int16, int32, int64, set_default_dtype, uint8,
    finfo, iinfo,
)
from .core.place import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)
from .core import flags as _flags  # noqa: F401
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.generator import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .autograd import grad, no_grad  # noqa: F401
from .autograd.tape import enable_grad  # noqa: F401

# op namespace: paddle.add / paddle.matmul / ...
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation
from .ops.creation import (  # noqa: F401
    arange, assign, bernoulli, empty, empty_like, eye, full, full_like,
    linspace, logspace, meshgrid, multinomial, normal, ones, ones_like, rand,
    randint, randn, randperm, uniform, zeros, zeros_like,
)

from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import distributed  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import vision  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import sparse  # noqa: F401
from . import static  # noqa: F401
from .framework.io import load, save  # noqa: F401
from .framework import device  # noqa: F401

import paddle_tpu.tensor as tensor  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import linalg  # noqa: F401
from . import text  # noqa: F401
from . import hapi  # noqa: F401
from . import distribution  # noqa: F401
from . import quantization  # noqa: F401
from . import models  # noqa: F401
from . import parallel  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from .ops.control_flow import case, cond, scan, switch_case, while_loop  # noqa: F401
from .autograd.py_layer import PyLayer, PyLayerContext  # noqa: F401
from .nn.initializer import ParamAttr  # noqa: F401

__version__ = "0.1.0"


def is_grad_enabled() -> bool:
    from .autograd.tape import grad_enabled
    return grad_enabled()


def disable_static(place=None):
    from .static.graph import disable_static as _ds
    return _ds(place)


def enable_static():
    from .static.graph import enable_static as _es
    return _es()


def in_dynamic_mode() -> bool:
    from .static.graph import in_static_mode
    return not in_static_mode()
