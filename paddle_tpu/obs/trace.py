"""Request-scoped tracing: named tracks, nested spans, async request
lifecycles, chrome://tracing JSON export.

Dapper-style (Sigelman et al., 2010) host-side tracing for the serving
stack: a ``Tracer`` collects timestamped events on named TRACKS (the
chrome-trace "thread" axis — the engine uses one track per decode slot
and one per tenant), and exports them as a chrome://tracing /
Perfetto-loadable JSON object. Timestamps come from a pluggable clock
so the serving engine's VIRTUAL clock (``EngineClock``) and wall time
(``time.perf_counter``) both work; durations are stored in clock
units (seconds for wall/measured clocks) and scaled to microseconds at
export, which is what the chrome trace format expects.

Event kinds map onto chrome trace phases:

- ``span`` / ``add_span``  -> complete events (ph "X"): nested work on
  one track (prefill, decode_n, a dense wave). Same-track spans must
  nest (contained or disjoint) — the engine emits them from a single
  sequential loop, so they do by construction.
- ``async_begin``/``async_end`` -> async events (ph "b"/"e"): REQUEST
  ROOT SPANS, which overlap freely on a tenant track (request B
  arrives before request A finishes).
- ``instant`` -> instant events (ph "i"): scheduler decisions (admit
  wave, shed, degrade), jit compiles.
- ``counter`` -> counter events (ph "C"): queue depth over time.

A process-global ACTIVE tracer (``use``/``activate``/``active``) lets
layers that cannot be threaded a tracer handle (the jit program cache,
``route_decode``) attach events to whatever trace is being recorded;
when none is active they fall through at the cost of one ``is None``
check. ``trace_id`` rides a contextvar: ``trace_scope(rid)`` tags
every span recorded inside with the owning request.

The profiler's span store (``paddle_tpu.profiler._spans``) is FED from
here too: while a ``profiler.Profiler`` is recording, every complete
span is mirrored into it, so ``Profiler.summary()`` tables include
obs spans without a second instrumentation pass.
"""
from __future__ import annotations

import contextvars
import json
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

_trace_id: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_obs_trace_id", default=None)


def get_trace_id() -> Optional[str]:
    """The request id owning the current context (None outside one)."""
    return _trace_id.get()


@contextmanager
def trace_scope(trace_id: str):
    """Tag every span/instant recorded inside with ``trace_id``."""
    tok = _trace_id.set(trace_id)
    try:
        yield
    finally:
        _trace_id.reset(tok)


class Tracer:
    """One trace: an event list plus a track-name -> tid registry.

    ``clock``: zero-arg callable returning the current time in this
    trace's units (default ``time.perf_counter``). The serving engine
    swaps in its virtual clock for the duration of a run.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.perf_counter
        self._events: List[dict] = []
        self._tracks: Dict[str, int] = {}
        self._mirror_profiler = True
        # the mirror seam: an optional per-event sink (the incident
        # flight recorder's bounded ring) fed alongside the event
        # list — one is-None check per recorded event, nothing when
        # tracing is off (no events are recorded at all then)
        self._sink: Optional[Callable[[dict], None]] = None

    # --- clock / tracks ---------------------------------------------------
    def set_clock(self, clock: Callable[[], float]):
        self._clock = clock

    def now(self) -> float:
        return self._clock()

    def track(self, name: str) -> int:
        """tid for a named track (assigned in first-use order, so track
        layout in the viewer follows instrumentation order)."""
        tid = self._tracks.get(name)
        if tid is None:
            tid = len(self._tracks) + 1
            self._tracks[name] = tid
        return tid

    def set_sink(self, sink: Optional[Callable[[dict], None]]):
        """Install (or clear, with None) the per-event mirror sink —
        ``obs.flight.FlightRecorder.attach`` uses this to keep a
        bounded ring of the most recent events."""
        self._sink = sink

    def _emit(self, evt: dict):
        self._events.append(evt)
        if self._sink is not None:
            self._sink(evt)

    # --- event emission ---------------------------------------------------
    def _args(self, attrs: dict) -> dict:
        tid = _trace_id.get()
        if tid is not None and "trace_id" not in attrs:
            attrs = dict(attrs, trace_id=tid)
        return attrs

    def add_span(self, name: str, t0: float, dur: float,
                 track: str = "main", **attrs):
        """A complete span with explicit start/duration (clock units)."""
        self._emit({"name": name, "ph": "X", "ts": t0,
                    "dur": max(dur, 0.0),
                    "tid": self.track(track),
                    "args": self._args(attrs)})
        if self._mirror_profiler:
            self._to_profiler(name, t0, dur)

    @contextmanager
    def span(self, name: str, track: str = "main", **attrs):
        """Context-managed span on this tracer's clock."""
        t0 = self.now()
        try:
            yield self
        finally:
            self.add_span(name, t0, self.now() - t0, track=track, **attrs)

    def instant(self, name: str, t: Optional[float] = None,
                track: str = "main", **attrs):
        self._emit({"name": name, "ph": "i",
                    "ts": self.now() if t is None else t,
                    "s": "t", "tid": self.track(track),
                    "args": self._args(attrs)})

    def counter(self, name: str, value: float,
                t: Optional[float] = None, track: str = "counters"):
        self._emit({"name": name, "ph": "C",
                    "ts": self.now() if t is None else t,
                    "tid": self.track(track),
                    "args": {"value": value}})

    def async_begin(self, name: str, id_: str,
                    t: Optional[float] = None, track: str = "main",
                    cat: str = "request", **attrs):
        """Open an async (overlap-capable) span, e.g. a request root."""
        self._emit({"name": name, "ph": "b", "cat": cat,
                    "id": str(id_),
                    "ts": self.now() if t is None else t,
                    "tid": self.track(track),
                    "args": self._args(attrs)})

    def async_end(self, name: str, id_: str,
                  t: Optional[float] = None, track: str = "main",
                  cat: str = "request", **attrs):
        self._emit({"name": name, "ph": "e", "cat": cat,
                    "id": str(id_),
                    "ts": self.now() if t is None else t,
                    "tid": self.track(track),
                    "args": self._args(attrs)})

    def _to_profiler(self, name, t0, dur):
        # feed the profiler's span store while a Profiler is recording
        # (its `enabled` flag); import lazily — profiler pulls in jax
        try:
            import sys
            prof = sys.modules.get("paddle_tpu.profiler")
            if prof is not None and prof._spans.enabled:
                prof._spans.add(name, t0, dur, self.track("main"))
        except Exception:
            pass

    # --- introspection / export -------------------------------------------
    def __len__(self) -> int:
        return len(self._events)

    @property
    def events(self) -> List[dict]:
        return list(self._events)

    def clear(self):
        """Empty the trace — events AND track registrations (a reused
        tracer must not export ghost tracks from a previous run; tids
        are re-derived on first use)."""
        self._events.clear()
        self._tracks.clear()

    def to_chrome(self, pid: int = 1,
                  process_name: str = "paddle_tpu") -> dict:
        """The chrome://tracing JSON object (ts/dur in microseconds)."""
        evts: List[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name}}]
        for name, tid in sorted(self._tracks.items(),
                                key=lambda kv: kv[1]):
            evts.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": name}})
            evts.append({"name": "thread_sort_index", "ph": "M",
                         "pid": pid, "tid": tid,
                         "args": {"sort_index": tid}})
        for e in self._events:
            out = dict(e, pid=pid, ts=round(e["ts"] * 1e6, 3))
            if "dur" in out:
                out["dur"] = round(out["dur"] * 1e6, 3)
            evts.append(out)
        return {"traceEvents": evts,
                "displayTimeUnit": "ms"}

    def export(self, path: str, pid: int = 1,
               process_name: str = "paddle_tpu") -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome(pid, process_name), f)
        return path


# --- the process-global active tracer -----------------------------------
_active: Optional[Tracer] = None


def active() -> Optional[Tracer]:
    """The tracer currently recording, or None (the common, free case)."""
    return _active


def activate(tracer: Tracer):
    global _active
    _active = tracer


def deactivate():
    global _active
    _active = None


@contextmanager
def use(tracer: Optional[Tracer]):
    """Install ``tracer`` as the process-global active tracer for the
    duration (None is allowed and is a no-op, so call sites need no
    branch)."""
    global _active
    prev = _active
    if tracer is not None:
        _active = tracer
    try:
        yield tracer
    finally:
        _active = prev
