"""Streaming SLO evaluation: declarative rules over the signals the
serving stack already emits, firing typed ``Incident`` objects.

PR 4 built the passive spine (``obs.trace`` / ``obs.metrics``); this is
the layer that EVALUATES it — the SRE half of observability (Google
SRE workbook ch. 5: multi-window burn-rate alerts over an error
budget), built for the repo's virtual-clock harness: every timestamp
is virtual, every evaluation order deterministic, so one seeded chaos
replay yields the SAME incident set byte-for-byte, twice.

Three rule kinds, all frozen declarative dataclasses (a rule object
carries no state, so one rule list can parameterize N per-replica
monitors):

- ``ThresholdRule``: a signal (a gauge sample like ``queue_depth``, or
  a per-request field like ``ttft``) breaches a bound, optionally
  sustained for ``for_units`` of virtual time. Fires once per breach
  episode; recovery closes the incident and re-arms.
- ``BurnRateRule``: multi-window burn rate over an error budget. With
  objective ``o`` (target good fraction), the error budget rate is
  ``1 - o``; over each trailing window the observed error rate divided
  by the budget rate is the BURN. The rule fires only when EVERY
  window burns above its threshold (the long window proves it is
  real, the short window proves it is still happening) with at least
  ``min_events`` in the shortest window — the standard fast+slow
  multiwindow alert, evaluated streaming on the virtual clock.
- ``HeartbeatRule``: the watched source has been silent (no heartbeat,
  no signal at all) for ``timeout`` units. A stalled-but-alive replica
  keeps answering probes and never trips this; a crashed one goes
  silent and does.

``SLOMonitor`` consumes the streams: per-request completion records
(``MetricsCollector`` feeds ``observe_request`` at finish/shed),
gauge samples (``observe_value``), heartbeats, and externally observed
fault events (``event`` — the cluster's crash/stall/failover
machinery auto-opens incidents through it). Incidents land in a
(shareable) ``IncidentLog`` with deterministic ``inc-NNNN`` ids
assigned in open order; ``on_incident`` callbacks are the subscription
seam (detect-and-report only — the QoS scheduler's
``note_incident`` is wired there so a later PR can degrade on page,
nothing degrades today). A monitor given a ``flight.FlightRecorder``
freezes a postmortem bundle the moment an incident opens.

No jax, no serving imports at module load (the JSONL loader borrows
``serving.workload.iter_jsonl_tolerant`` lazily).
"""
from __future__ import annotations

import dataclasses
import json
import os
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

SEVERITIES = ("warn", "page")


def _atomic_write(path: str, text: str):
    """The repo's tmp+``os.replace`` write discipline (see
    framework/io.py save): parents created, a crash mid-write can
    never leave a truncated file where the old one was. Shared with
    ``obs.flight``'s bundle writer."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)

# what the per-request predicates call "bad": a missed deadline (sheds
# included — a shed request can never meet its SLO), a shed itself, or
# a deadline-timeout eviction
BAD_PREDICATES = ("deadline_missed", "shed", "timeout")


def _is_bad(pred: str, view: dict) -> Optional[bool]:
    """True/False = counts as bad/good for the burn stream; None = the
    record carries no verdict for this predicate (not counted)."""
    if pred == "deadline_missed":
        met = view.get("deadline_met")
        return None if met is None else (not met)
    if pred == "shed":
        return bool(view.get("shed"))
    if pred == "timeout":
        return view.get("finish_reason") == "timeout"
    raise ValueError(f"unknown bad-predicate {pred!r}")


@dataclasses.dataclass(frozen=True)
class ThresholdRule:
    """``signal`` ``op`` ``bound``, sustained ``for_units`` -> fire."""

    name: str
    signal: str
    bound: float
    op: str = ">="
    for_units: float = 0.0
    severity: str = "warn"
    kind: str = dataclasses.field(default="threshold", init=False)

    def __post_init__(self):
        if self.op not in (">=", "<="):
            raise ValueError(f"threshold op {self.op!r}: use '>=' or "
                             "'<='")
        if self.for_units < 0:
            raise ValueError("for_units must be >= 0")
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r}: use one of "
                             f"{SEVERITIES}")

    def breaches(self, value: float) -> bool:
        return value >= self.bound if self.op == ">=" \
            else value <= self.bound


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """Multi-window error-budget burn over a good/bad event stream.

    ``objective``: target good fraction (0.99 -> 1% error budget).
    ``windows``: ((window_units, burn_threshold), ...) — EVERY window
    must burn above its threshold to fire (classic long+short pair).
    ``bad``: the per-request predicate naming the bad event
    (``deadline_missed`` / ``shed`` / ``timeout``).
    ``min_events``: events required in the SHORTEST window before the
    rule may fire (no alert on 2-of-3 bad).
    """

    name: str
    objective: float
    windows: Tuple[Tuple[float, float], ...] = ((60.0, 10.0),
                                                (12.0, 10.0))
    bad: str = "deadline_missed"
    min_events: int = 20
    severity: str = "page"
    kind: str = dataclasses.field(default="burn_rate", init=False)

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1) — it is the "
                             "target GOOD fraction")
        if not self.windows:
            raise ValueError("burn-rate rule needs >= 1 window")
        for w, thr in self.windows:
            if w <= 0 or thr <= 0:
                raise ValueError("windows are (positive span, positive "
                                 "burn threshold) pairs")
        if self.bad not in BAD_PREDICATES:
            raise ValueError(f"bad={self.bad!r}: use one of "
                             f"{BAD_PREDICATES}")
        if self.min_events < 1:
            raise ValueError("min_events must be >= 1")
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r}: use one of "
                             f"{SEVERITIES}")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


@dataclasses.dataclass(frozen=True)
class HeartbeatRule:
    """The watched source silent for ``timeout`` units -> fire."""

    name: str
    timeout: float
    severity: str = "page"
    kind: str = dataclasses.field(default="heartbeat_silence",
                                  init=False)

    def __post_init__(self):
        if self.timeout <= 0:
            raise ValueError("heartbeat timeout must be > 0")
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r}: use one of "
                             f"{SEVERITIES}")


@dataclasses.dataclass
class Incident:
    """One fired rule or observed fault, with its window evidence.
    Times are VIRTUAL clock units; ids are assigned by the owning
    ``IncidentLog`` in open order (``inc-NNNN``) — deterministic, so
    two replays of one seeded trace produce byte-identical incident
    sets. ``t_close`` stays None while the incident is open."""

    id: str
    rule: str
    kind: str
    severity: str
    t_open: float
    source: Optional[str] = None
    t_close: Optional[float] = None
    resolution: Optional[str] = None
    evidence: dict = dataclasses.field(default_factory=dict)
    rids: List[str] = dataclasses.field(default_factory=list)

    @property
    def open(self) -> bool:
        return self.t_close is None

    def close(self, t: float, resolution: str):
        if self.t_close is None:
            self.t_close = round(float(t), 6)
            self.resolution = resolution

    def act(self, t: float, action: str):
        """An automated responder (the autoscaling control plane)
        ACTED on this incident: the action is stamped into the
        evidence (``action_taken``) and the incident closes with
        resolution ``"action_taken"`` — so the postmortem reader sees
        not just that the alert fired but WHICH remediation resolved
        it. Idempotent like ``close``: an already-closed incident is
        left as the first resolution recorded it."""
        if self.t_close is None:
            self.evidence["action_taken"] = action
            self.close(t, "action_taken")

    def to_json(self) -> dict:
        d = {"id": self.id, "rule": self.rule, "kind": self.kind,
             "severity": self.severity, "source": self.source,
             "t_open": self.t_open, "t_close": self.t_close,
             "resolution": self.resolution,
             "evidence": self.evidence}
        if self.rids:
            d["rids"] = list(self.rids)
        return d

    @staticmethod
    def from_json(d: dict) -> "Incident":
        return Incident(id=str(d["id"]), rule=str(d["rule"]),
                        kind=str(d["kind"]),
                        severity=str(d["severity"]),
                        t_open=float(d["t_open"]),
                        source=d.get("source"),
                        t_close=d.get("t_close"),
                        resolution=d.get("resolution"),
                        evidence=dict(d.get("evidence") or {}),
                        rids=list(d.get("rids") or ()))


class IncidentLog:
    """Ordered incident ledger, shareable across N per-replica
    monitors (the cluster hands every monitor ONE log, so ids stay
    cluster-unique and open-order deterministic). ``save`` is the
    JSONL dump under the repo's atomic tmp+``os.replace`` discipline;
    ``load`` tolerates a torn FINAL line via the shared
    ``workload.iter_jsonl_tolerant`` policy."""

    def __init__(self):
        self.incidents: List[Incident] = []

    def open(self, *, rule: str, kind: str, severity: str, t: float,
             source: Optional[str] = None, evidence: Optional[dict]
             = None, rids: Sequence[str] = ()) -> Incident:
        inc = Incident(id=f"inc-{len(self.incidents):04d}", rule=rule,
                       kind=kind, severity=severity,
                       t_open=round(float(t), 6), source=source,
                       evidence=dict(evidence or {}),
                       rids=list(rids))
        self.incidents.append(inc)
        return inc

    def __len__(self):
        return len(self.incidents)

    def __iter__(self):
        return iter(self.incidents)

    def by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for inc in self.incidents:
            out[inc.kind] = out.get(inc.kind, 0) + 1
        return dict(sorted(out.items()))

    def save(self, path: str) -> str:
        _atomic_write(path, "".join(json.dumps(inc.to_json()) + "\n"
                                    for inc in self.incidents))
        return path

    @staticmethod
    def load(path: str) -> List[Incident]:
        return list(load_incidents(path))


def load_incidents(path: str) -> List[Incident]:
    """Parse a ``save``d incident JSONL. A torn FINAL line (crashing
    writer) warns and returns the valid prefix; a malformed earlier
    line raises — shared policy with traces and engine logs."""
    from ..serving.workload import iter_jsonl_tolerant
    return [Incident.from_json(d) for d in iter_jsonl_tolerant(path)]


class _Window:
    """One trailing window's INCREMENTAL event bookkeeping: each
    event is appended once and expired once, so evaluation is O(1)
    amortized per signal instead of rescanning the window — at
    10^5-request cluster scale the monitor advances on every
    observation and every heartbeat, and a rescan there is
    O(events-in-window) per advance."""

    __slots__ = ("span", "threshold", "events", "n", "bad")

    def __init__(self, span: float, threshold: float):
        self.span = span
        self.threshold = threshold
        self.events: deque = deque()   # (t, bad: 0/1) in time order
        self.n = 0
        self.bad = 0

    def add(self, t: float, bad: int):
        self.events.append((t, bad))
        self.n += 1
        self.bad += bad

    def expire(self, t: float):
        # keep events with et >= t - span (edge inclusive, matching
        # the epsilon the streaming tests pin down)
        cut = t - self.span - 1e-12
        ev = self.events
        while ev and ev[0][0] < cut:
            _, b = ev.popleft()
            self.n -= 1
            self.bad -= b

    def burn(self, budget: float) -> float:
        return (self.bad / self.n) / budget if self.n else 0.0

    def evidence(self, budget: float) -> dict:
        err = (self.bad / self.n) if self.n else 0.0
        return {"window": self.span, "threshold": self.threshold,
                "events": self.n, "bad": self.bad,
                "error_rate": round(err, 6),
                "burn": round(err / budget, 6)}


class _BurnState:
    __slots__ = ("windows", "cum", "cum_bad", "open_inc", "bad_rids")

    def __init__(self, rule: "BurnRateRule"):
        self.windows = [_Window(w, thr)
                        for w, thr in sorted(rule.windows,
                                             reverse=True)]
        self.cum = 0
        self.cum_bad = 0
        self.open_inc: Optional[Incident] = None
        self.bad_rids: deque = deque(maxlen=16)


class _ThresholdState:
    __slots__ = ("breach_since", "open_inc", "last_value", "last_rid")

    def __init__(self):
        self.breach_since: Optional[float] = None
        self.open_inc: Optional[Incident] = None
        self.last_value: Optional[float] = None
        self.last_rid: Optional[str] = None


class SLOMonitor:
    """Streaming evaluation of one source's SLO rules.

    Feed it the signals the system already produces — per-request
    records at finish/shed (``observe_request``), gauge samples
    (``observe_value``), liveness (``heartbeat``) — and drive time
    forward with ``advance``; rules evaluate as the stream arrives,
    incidents land in ``log``. ``event`` is the externally-observed
    fault path (the cluster's crash/stall/decode-error/failover
    machinery): it ALWAYS opens an incident (one per observed event —
    the exactly-once accounting the chaos gate checks), optionally
    self-closing at ``close_t``.

    A monitor observes and reports; it never mutates the system it
    watches — engine outputs, slot logs and metrics records are
    byte-identical with a monitor attached or not (gated by
    ``bench_gate.py obs``'s ``obs_slo`` family). ``on_incident``
    callbacks are the degradation seam: subscribers (e.g.
    ``QoSScheduler.note_incident``) receive each incident as it
    opens.
    """

    def __init__(self, rules: Sequence = (), *,
                 source: Optional[str] = None, t0: float = 0.0,
                 log: Optional[IncidentLog] = None, flight=None,
                 on_incident: Sequence[Callable] = ()):
        self.rules = list(rules)
        for r in self.rules:
            if not isinstance(r, (ThresholdRule, BurnRateRule,
                                  HeartbeatRule)):
                raise ValueError(f"unknown rule type "
                                 f"{type(r).__name__} — use "
                                 "ThresholdRule / BurnRateRule / "
                                 "HeartbeatRule")
        names = [r.name for r in self.rules]
        if len(names) != len(set(names)):
            raise ValueError("rule names must be unique within one "
                             "monitor")
        self.source = source
        self.log = log if log is not None else IncidentLog()
        self.flight = flight
        self._cbs = list(on_incident)
        self.t = float(t0)
        self.last_beat = float(t0)
        self.retired = False
        self._burn: Dict[str, _BurnState] = {
            r.name: _BurnState(r) for r in self.rules
            if isinstance(r, BurnRateRule)}
        self._thr: Dict[str, _ThresholdState] = {
            r.name: _ThresholdState() for r in self.rules
            if isinstance(r, ThresholdRule)}
        self._hb_open: Dict[str, Incident] = {}
        # event incidents left open by `event(close_t=...)` waiting for
        # their scheduled close, ordered by close time
        self._timed_open: List[Tuple[float, Incident]] = []

    def reset(self, t0: float = 0.0):
        """Fresh monitoring session over the same rules — the
        ``trace=Tracer`` convention: ``ServingEngine.run`` resets a
        caller-held monitor at each run's start, so a replay's low
        virtual timestamps are not instantly expired by the previous
        run's windows and ``ServeResult.incidents`` never re-reports
        an earlier run. Clears the incident log IN PLACE (callers
        sharing one log across monitors — the cluster's per-replica
        pattern — build fresh monitors instead of resetting)."""
        self.log.incidents.clear()
        self.t = float(t0)
        self.last_beat = float(t0)
        self.retired = False
        self._burn = {r.name: _BurnState(r) for r in self.rules
                      if isinstance(r, BurnRateRule)}
        self._thr = {r.name: _ThresholdState() for r in self.rules
                     if isinstance(r, ThresholdRule)}
        self._hb_open = {}
        self._timed_open = []

    # --- incident plumbing --------------------------------------------------
    def _open(self, *, rule: str, kind: str, severity: str, t: float,
              evidence: Optional[dict] = None,
              rids: Sequence[str] = ()) -> Incident:
        inc = self.log.open(rule=rule, kind=kind, severity=severity,
                            t=t, source=self.source,
                            evidence=evidence, rids=rids)
        for cb in self._cbs:
            cb(inc)
        if self.flight is not None:
            self.flight.on_incident(inc)
        return inc

    def subscribe(self, cb: Callable):
        """Add an incident callback (the degradation seam)."""
        self._cbs.append(cb)

    # --- signal feeds -------------------------------------------------------
    def heartbeat(self, t: float):
        """The source answered a liveness probe at ``t``. Closes any
        open silence incident (the source came back)."""
        if self.retired:
            return
        t = float(t)
        self.last_beat = max(self.last_beat, t)
        for name, inc in list(self._hb_open.items()):
            inc.close(t, "heartbeat_resumed")
            del self._hb_open[name]
        self.advance(t)

    def observe_request(self, view: dict, t: float):
        """One request reached its FINAL state (finish or shed) at
        ``t``; ``view`` is its ``MetricsCollector.request`` record
        (plus ``rid``). Feeds every burn-rate stream and any
        request-field threshold rule; any signal from the source also
        proves it alive."""
        if self.retired:
            return
        t = float(t)
        self.last_beat = max(self.last_beat, t)
        rid = view.get("rid")
        if self.flight is not None:
            # ring BEFORE evaluating: the observation that trips a
            # rule must be inside the frozen bundle
            for k in ("ttft", "tpot"):
                if view.get(k) is not None:
                    self.flight.sample(k, view[k], t,
                                       source=self.source)
        for r in self.rules:
            if isinstance(r, BurnRateRule):
                bad = _is_bad(r.bad, view)
                if bad is None:
                    continue
                st = self._burn[r.name]
                for w in st.windows:
                    w.add(t, 1 if bad else 0)
                st.cum += 1
                st.cum_bad += 1 if bad else 0
                if bad and rid is not None:
                    st.bad_rids.append((t, rid))
            elif isinstance(r, ThresholdRule) \
                    and r.signal in view \
                    and view[r.signal] is not None:
                self._thr_observe(r, float(view[r.signal]), t, rid=rid)
        self.advance(t)

    def observe_value(self, name: str, value: float, t: float):
        """One gauge/counter sample (queue depth, lane depth, ...)."""
        if self.retired:
            return
        t = float(t)
        self.last_beat = max(self.last_beat, t)
        if self.flight is not None:
            # ring before evaluating (see observe_request)
            self.flight.sample(name, value, t, source=self.source)
        for r in self.rules:
            if isinstance(r, ThresholdRule) and r.signal == name:
                self._thr_observe(r, float(value), t)
        self.advance(t)

    def event(self, kind: str, t: float, *, severity: str = "page",
              close_t: Optional[float] = None,
              evidence: Optional[dict] = None,
              rids: Sequence[str] = ()) -> Optional[Incident]:
        """An externally observed fault (crash/stall/decode_error/
        failover/...): auto-open one incident per event. ``close_t``
        schedules an automatic close (a stall's known end); ``close_t
        <= t`` closes immediately (a point event). Without it the
        incident stays open until ``close_kind`` / ``retire``."""
        if self.retired:
            return None
        if severity not in SEVERITIES:
            raise ValueError(f"severity {severity!r}: use one of "
                             f"{SEVERITIES}")
        t = float(t)
        inc = self._open(rule=kind, kind=kind, severity=severity,
                         t=t, evidence=evidence, rids=rids)
        if close_t is not None:
            if close_t <= t:
                inc.close(t, "event_complete")
            else:
                self._timed_open.append((float(close_t), inc))
                self._timed_open.sort(key=lambda p: p[0])
        self.advance(t)
        return inc

    def close_kind(self, kind: str, t: float, resolution: str) -> int:
        """Close every open incident of ``kind`` from this source
        (e.g. the crash incident once failover completes). Returns
        how many closed."""
        n = 0
        for inc in self.log.incidents:
            if inc.open and inc.kind == kind \
                    and inc.source == self.source:
                inc.close(t, resolution)
                n += 1
        self._timed_open = [(ct, i) for ct, i in self._timed_open
                            if i.open]
        return n

    # --- evaluation ---------------------------------------------------------
    def _thr_observe(self, r: ThresholdRule, value: float, t: float,
                     rid: Optional[str] = None):
        st = self._thr[r.name]
        prev = st.last_value
        st.last_value = value
        if r.breaches(value):
            if st.breach_since is None:
                st.breach_since = t
            st.last_rid = rid
            if st.open_inc is None \
                    and t - st.breach_since >= r.for_units - 1e-12:
                ev = {"signal": r.signal, "value": round(value, 6),
                      "bound": r.bound, "op": r.op,
                      "breach_since": round(st.breach_since, 6)}
                st.open_inc = self._open(
                    rule=r.name, kind=r.kind, severity=r.severity,
                    t=t, evidence=ev,
                    rids=[rid] if rid is not None else ())
        else:
            if st.open_inc is None and st.breach_since is not None \
                    and t - st.breach_since >= r.for_units - 1e-12:
                # the breach SUSTAINED past for_units but no other
                # signal advanced the clock mid-episode — the
                # recovering sample itself is the first evaluation
                # point, so the episode fires retroactively (with the
                # last BREACHING value as evidence) and closes at the
                # recovery. Detection must not depend on unrelated
                # traffic happening to arrive mid-breach.
                ev = {"signal": r.signal,
                      "value": round(prev, 6) if prev is not None
                      else None,
                      "bound": r.bound, "op": r.op,
                      "breach_since": round(st.breach_since, 6)}
                st.open_inc = self._open(
                    rule=r.name, kind=r.kind, severity=r.severity,
                    t=t, evidence=ev,
                    rids=[st.last_rid]
                    if st.last_rid is not None else ())
            st.breach_since = None
            st.last_rid = None
            if st.open_inc is not None:
                st.open_inc.close(t, "recovered")
                st.open_inc = None

    def advance(self, t: float):
        """Drive virtual time to ``t`` and evaluate every time-based
        rule: scheduled event closes, burn-rate windows, heartbeat
        silence, sustained thresholds."""
        if self.retired:
            return
        t = max(self.t, float(t))
        self.t = t
        while self._timed_open and self._timed_open[0][0] <= t + 1e-12:
            ct, inc = self._timed_open.pop(0)
            inc.close(ct, "event_complete")
        for r in self.rules:
            if isinstance(r, BurnRateRule):
                st = self._burn[r.name]
                budget = r.budget
                for w in st.windows:
                    w.expire(t)
                # windows are sorted longest-first; the SHORTEST
                # carries the min_events guard
                firing = (st.windows[-1].n >= r.min_events
                          and all(w.burn(budget) >= w.threshold
                                  for w in st.windows))
                if firing and st.open_inc is None:
                    budget_spent = (st.cum_bad / (st.cum * budget)) \
                        if st.cum else 0.0
                    st.open_inc = self._open(
                        rule=r.name, kind=r.kind, severity=r.severity,
                        t=t,
                        evidence={"objective": r.objective,
                                  "windows": [w.evidence(budget)
                                              for w in st.windows],
                                  "cum_events": st.cum,
                                  "cum_bad": st.cum_bad,
                                  "budget_spent":
                                  round(budget_spent, 6)},
                        # offending rids: only bad requests still
                        # inside the LONGEST firing window — a
                        # long-recovered burst must not send the
                        # postmortem reader to unrelated requests
                        rids=[rid for et, rid in st.bad_rids
                              if et >= t - st.windows[0].span
                              - 1e-12])
                elif not firing and st.open_inc is not None \
                        and all(w.burn(budget) < w.threshold
                                for w in st.windows):
                    st.open_inc.close(t, "burn_recovered")
                    st.open_inc = None
            elif isinstance(r, HeartbeatRule):
                silent = t - self.last_beat
                if silent >= r.timeout - 1e-9 \
                        and r.name not in self._hb_open:
                    self._hb_open[r.name] = self._open(
                        rule=r.name, kind=r.kind, severity=r.severity,
                        t=t,
                        evidence={"silent_for": round(silent, 6),
                                  "timeout": r.timeout,
                                  "last_beat":
                                  round(self.last_beat, 6)})
            elif isinstance(r, ThresholdRule):
                st = self._thr[r.name]
                if st.open_inc is None and st.breach_since is not None \
                        and st.last_value is not None \
                        and t - st.breach_since \
                        >= r.for_units - 1e-12:
                    ev = {"signal": r.signal,
                          "value": round(st.last_value, 6),
                          "bound": r.bound, "op": r.op,
                          "breach_since": round(st.breach_since, 6)}
                    st.open_inc = self._open(
                        rule=r.name, kind=r.kind, severity=r.severity,
                        t=t, evidence=ev,
                        rids=[st.last_rid]
                        if st.last_rid is not None else ())

    def retire(self, t: float, resolution: str = "source_removed"):
        """The watched source left the system (drain retirement or
        crash failover): close every incident still open from it and
        stop evaluating — a removed replica's silence is not an
        alert."""
        if self.retired:
            return
        for inc in self.log.incidents:
            if inc.open and inc.source == self.source:
                inc.close(t, resolution)
        self._hb_open.clear()
        self._timed_open = []
        for st in self._burn.values():
            st.open_inc = None
        for st in self._thr.values():
            st.open_inc = None
        self.retired = True

    @property
    def incidents(self) -> List[Incident]:
        """Every incident in the (possibly shared) log."""
        return list(self.log.incidents)


def default_serving_rules(*, objective: float = 0.85,
                          burn_threshold: float = 4.0,
                          long_window: float = 400.0,
                          short_window: float = 80.0,
                          min_events: int = 200,
                          queue_bound: Optional[float] = None) \
        -> List[object]:
    """The stock rule set the serving bench and docs share: a
    fast+slow deadline-attainment burn alert, a shed-storm burn alert
    (shedding is admission-time SLO loss — a crash's failover surge
    shows up here first), and optionally a queue-depth threshold.
    Calibrated against the seeded 10^5-request chaos trace: the
    fault-free replay fires NOTHING (the zero-false-positive gate),
    the crash replay's shed/deadline storms fire deterministically."""
    rules: List[object] = [
        BurnRateRule(name="deadline_burn", objective=objective,
                     windows=((long_window, burn_threshold),
                              (short_window, burn_threshold)),
                     bad="deadline_missed", min_events=min_events,
                     severity="page"),
        BurnRateRule(name="shed_burn", objective=objective,
                     windows=((long_window, burn_threshold),
                              (short_window, burn_threshold)),
                     bad="shed", min_events=min_events,
                     severity="warn"),
    ]
    if queue_bound is not None:
        rules.append(ThresholdRule(name="queue_depth_high",
                                   signal="queue_depth",
                                   bound=float(queue_bound),
                                   op=">=", severity="warn"))
    return rules
