"""Incident flight recorder: an always-on bounded ring of recent
trace events + metric samples, frozen into a postmortem bundle the
moment an SLO incident fires.

The black-box-recorder pattern: full tracing at production scale is
too heavy to leave on, but the moments you need are exactly the ones
you cannot predict — so keep the LAST N spans and samples in O(1)
memory (two ``deque(maxlen=...)`` rings), and when ``slo.SLOMonitor``
opens an incident, freeze the rings into a replayable bundle on disk:

    <bundle_dir>/<incident-id>/
        incident.json     the typed Incident record
        trace.json        chrome://tracing excerpt (the span ring +
                          thread-name metadata — loads in Perfetto)
        metrics.jsonl     the sample ring, one JSONL line per sample
        requests.json     the offending request ids

Every file is written under the repo's atomic tmp+``os.replace``
discipline, and every value in a bundle comes from the VIRTUAL clock,
so two replays of one seeded trace produce byte-identical bundles
(paths aside). ``load_bundle`` reads it back, tolerating a torn final
``metrics.jsonl`` line via the shared
``workload.iter_jsonl_tolerant`` policy.

Span capture reuses the Tracer mirror seam from PR 4: ``attach(tr)``
installs the ring as the tracer's event sink (the same pattern that
feeds the profiler's span store), so the recorder sees every span /
instant / counter the engine emits with zero extra instrumentation.
With no tracer attached the span ring stays empty and bundles carry
only samples — the recorder itself never forces tracing on.
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import List, Optional

from .slo import _atomic_write


class FlightRecorder:
    """Bounded recent-history rings + the bundle writer.

    ``span_capacity`` / ``sample_capacity`` bound memory regardless of
    run length. ``bundle_dir`` (optional) arms automatic bundle writes
    on incident open (``slo.SLOMonitor`` calls ``on_incident``);
    without it the recorder still rings — ``write_bundle`` can be
    called manually."""

    def __init__(self, *, span_capacity: int = 2048,
                 sample_capacity: int = 2048,
                 bundle_dir: Optional[str] = None):
        if span_capacity < 1 or sample_capacity < 1:
            raise ValueError("ring capacities must be >= 1")
        self._events: deque = deque(maxlen=int(span_capacity))
        self._samples: deque = deque(maxlen=int(sample_capacity))
        self.bundle_dir = bundle_dir
        self.bundles_written: List[str] = []
        self._tracer = None

    # --- feeds -------------------------------------------------------------
    def attach(self, tracer) -> "FlightRecorder":
        """Mirror every event ``tracer`` records into the span ring
        (the PR-4 mirror seam: ``Tracer.set_sink``)."""
        tracer.set_sink(self.on_event)
        self._tracer = tracer
        return self

    def on_event(self, evt: dict):
        """Tracer sink: one raw trace event (span/instant/counter/
        async begin-end), already timestamped in virtual units."""
        self._events.append(evt)

    def sample(self, name: str, value, t: float,
               source: Optional[str] = None):
        """One metric sample (queue depth, a request's TTFT, ...)."""
        rec = {"t": round(float(t), 6), "name": name,
               "value": round(float(value), 6)}
        if source is not None:
            rec["source"] = source
        self._samples.append(rec)

    # --- freeze ------------------------------------------------------------
    def snapshot(self) -> dict:
        """A frozen copy of both rings (plus the attached tracer's
        track registry, so the chrome excerpt keeps its lane names)."""
        tracks = dict(self._tracer._tracks) \
            if self._tracer is not None else {}
        return {"events": [dict(e) for e in self._events],
                "samples": [dict(s) for s in self._samples],
                "tracks": tracks}

    def _chrome_excerpt(self, snap: dict) -> dict:
        evts: List[dict] = [{"name": "process_name", "ph": "M",
                             "pid": 1, "tid": 0,
                             "args": {"name": "paddle_tpu_flight"}}]
        for name, tid in sorted(snap["tracks"].items(),
                                key=lambda kv: kv[1]):
            evts.append({"name": "thread_name", "ph": "M", "pid": 1,
                         "tid": tid, "args": {"name": name}})
        for e in snap["events"]:
            out = dict(e, pid=1, ts=round(e["ts"] * 1e6, 3))
            if "dur" in out:
                out["dur"] = round(out["dur"] * 1e6, 3)
            evts.append(out)
        return {"traceEvents": evts, "displayTimeUnit": "ms"}

    def on_incident(self, incident) -> Optional[str]:
        """``slo.SLOMonitor``'s hook: freeze + write a bundle when
        armed with a ``bundle_dir`` (no-op otherwise — the rings keep
        rolling either way)."""
        if self.bundle_dir is None:
            return None
        return self.write_bundle(incident)

    def write_bundle(self, incident,
                     out_dir: Optional[str] = None) -> str:
        """Freeze the rings and write the four-file postmortem bundle
        for ``incident`` under ``out_dir`` (default
        ``<bundle_dir>/<incident.id>``). Atomic per file; returns the
        bundle directory."""
        base = out_dir if out_dir is not None else \
            os.path.join(self.bundle_dir or ".", incident.id)
        os.makedirs(base, exist_ok=True)
        snap = self.snapshot()
        _atomic_write(os.path.join(base, "incident.json"),
                      json.dumps(incident.to_json(), indent=2) + "\n")
        _atomic_write(os.path.join(base, "trace.json"),
                      json.dumps(self._chrome_excerpt(snap)) + "\n")
        _atomic_write(os.path.join(base, "metrics.jsonl"),
                      "".join(json.dumps(s) + "\n"
                              for s in snap["samples"]))
        _atomic_write(os.path.join(base, "requests.json"),
                      json.dumps({"rids": list(incident.rids)},
                                 indent=2) + "\n")
        self.bundles_written.append(base)
        return base


def load_bundle(path: str) -> dict:
    """Read a bundle back: ``{"incident", "trace_events", "samples",
    "rids"}``. ``metrics.jsonl`` loads through the shared tolerant
    JSONL policy (a torn final line — the file a crashing process
    leaves — warns and yields the valid prefix; an earlier tear
    raises). Missing optional files load as empty."""
    from ..serving.workload import iter_jsonl_tolerant
    from .slo import Incident
    with open(os.path.join(path, "incident.json")) as f:
        incident = Incident.from_json(json.load(f))
    out = {"incident": incident, "trace_events": [], "samples": [],
           "rids": []}
    tp = os.path.join(path, "trace.json")
    if os.path.exists(tp):
        with open(tp) as f:
            out["trace_events"] = json.load(f).get("traceEvents", [])
    mp = os.path.join(path, "metrics.jsonl")
    if os.path.exists(mp) and os.path.getsize(mp):
        out["samples"] = list(iter_jsonl_tolerant(mp))
    rp = os.path.join(path, "requests.json")
    if os.path.exists(rp):
        with open(rp) as f:
            out["rids"] = json.load(f).get("rids", [])
    return out
