"""paddle_tpu.obs.ledger — the resource-attribution ledger.

The serving stack prices every action on the virtual clock and budgets
four resource pools; this module attributes that cost back to who
incurred it. Two streams feed one :class:`CostLedger`:

- **clock charges**: every priced ``EngineClock.timed`` delta, tagged
  at the call site with ``(rid | "engine", kind)``. Batched dispatches
  (a decode turn over N rows, a ragged-fused prefill) split pro-rata
  across the dispatched rows — the ``timed(cost=[...])`` list-splitting
  convention extended with an attribution vector. Idle jumps
  (``advance_to``) land in a per-engine ``idle`` book. A priced call
  that reaches the clock with NO attribution lands in the
  ``unattributed`` bucket, which the audit requires to be zero.
- **occupancy integrals**: once per engine turn the sampler books who
  held each budgeted pool slot for that turn — device KV pages per
  holding request (shared prefix pages split across holders),
  adapter/grammar pinned slots per pin owner, host-arena entries per
  preemption owner — against a pool-side integral read from the same
  population counts the census checks use.

All books are INTEGER nano-units (``SCALE`` per clock unit /
slot-turn), every delta fully distributed (pro-rata floor with the
residual on the last row), so the headline invariants hold **exactly**,
per engine, on any clock::

    sum(attributed units) + idle == elapsed clock units
    sum(per-owner slot-turns)    == per-turn pool-occupancy integral

Accounts are keyed by rid in ONE shared ledger, so a handoff, failover
or preemption moves a request's open account exactly once — the source
engine's charges stay on its book (work actually burned there), the
destination's accrue to the same account; nothing is lost or
double-counted at any membership change.

Also here: the shared budgeted-cache census arithmetic
(:func:`census_balanced`, :func:`overlay_contained`) that
``PagedKVCache`` / ``AdapterCache`` / ``GrammarCache`` / ``HostArena``
``census_ok()`` delegate to — the occupancy sampler reads the same
population counts, so the time books and the space books can never
disagree about what "resident" means.

Attribution rules, the invariant definitions and their composition
with chaos/disagg/preempt live in docs/OBSERVABILITY.md.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from .slo import _atomic_write

# one clock unit / one slot-turn, in ledger integer units. Every
# booked delta is quantized once (round-half-even at nano precision)
# and then distributed EXACTLY — conservation is integer arithmetic,
# never float summation.
SCALE = 10 ** 9

# kind -> feature dimension (the per-feature rollup is a PARTITION by
# kind: base prefill/decode land on "base", transform-priced kinds on
# their transform — so feature rows sum to the attributed total)
KIND_FEATURE = {
    "adapter_upload": "lora",
    "grammar_compile": "grammar",
    "spec_decode": "spec",
    "spec_prefill": "spec",
    "kv_pageout": "hostmem",
    "kv_pagein": "hostmem",
    "kv_transfer": "disagg",
    # the heterogeneous-handoff transform steps (reshard-on-import):
    # distinct attributable kinds, one feature — the disagg machinery
    # priced them, whichever axis mismatched
    "kv_reshard": "disagg",
    "kv_repage": "disagg",
    "kv_transcode": "disagg",
}

# non-request owners a charge/occupancy entry may carry: engine-owned
# priced work (e.g. a pressure pageout with no single beneficiary),
# the prefix cache's retained pages, and the audit-must-be-zero bucket
_SYSTEM_OWNERS = ("engine", "cache", "unattributed")


def census_balanced(capacity: int, *populations: int) -> bool:
    """The budgeted-cache conservation arithmetic every pool shares:
    the disjoint populations (resident/pinned, evictable, free — or
    stored/free bytes) partition the capacity exactly."""
    return sum(int(p) for p in populations) == int(capacity)


def overlay_contained(overlay, *tiers) -> bool:
    """An overlay population (e.g. the int8 KV tier) may only mark
    members that exist in one of the base tiers — nothing quantized
    may be free."""
    return all(any(k in t for t in tiers) for k in overlay)


def _quantize(delta: float) -> int:
    return int(round(float(delta) * SCALE))


def _split(u: int, n: int, weights=None) -> List[int]:
    """Distribute ``u`` integer units over ``n`` rows, exactly:
    pro-rata by ``weights`` when given (the ``cost=[...]`` vector of a
    fused dispatch), equal otherwise; floors everywhere with the
    residual on the LAST row (deterministic — rows arrive in slot
    order)."""
    if n <= 0:
        return []
    if weights is not None and len(weights) == n:
        tot = float(sum(weights))
        if tot > 0:
            shares = [int(u * float(w) / tot) for w in weights[:-1]]
            shares.append(u - sum(shares))
            if all(s >= 0 for s in shares):
                return shares
    q, rem = divmod(u, n)
    return [q] * (n - 1) + [q + rem]


# the per-turn occupancy sampler splits SCALE among a page's holders
# for every resident page — memoise the (tiny) family of even splits
# it ever asks for, so the hot loop costs a dict hit, not arithmetic
_EVEN_SCALE_SPLITS: Dict[int, List[int]] = {}


def _split_scale(n: int) -> List[int]:
    shares = _EVEN_SCALE_SPLITS.get(n)
    if shares is None:
        shares = _EVEN_SCALE_SPLITS[n] = _split(SCALE, n)
    return shares


class CostLedger:
    """Per-request / per-tenant / per-feature cost accounting with
    conservation audits. One instance may be shared across every
    engine/session/replica of a run (the cluster router does) — books
    are per engine, accounts are global by rid."""

    def __init__(self):
        # engine label -> {"elapsed": int, "idle": int,
        #                  "charges": {(owner, kind): int}}
        self._books: Dict[str, dict] = {}
        # rid -> {"tenant", "features": set, "outcomes": [..],
        #         "est": float|None}
        self._accounts: Dict[str, dict] = {}
        # engine -> {(owner, tier): int} / {tier: int}
        self._occ: Dict[str, Dict[Tuple[str, str], int]] = {}
        self._occ_pool: Dict[str, Dict[str, int]] = {}
        self._turns: Dict[str, int] = {}
        # prometheus watermarks: metric key -> last published int
        self._published: Dict[tuple, int] = {}

    # --- accounts ---------------------------------------------------------
    def _account(self, rid: str) -> dict:
        acct = self._accounts.get(rid)
        if acct is None:
            acct = {"tenant": None, "features": set(),
                    "outcomes": [], "est": None}
            self._accounts[rid] = acct
        return acct

    def open(self, rid: str, tenant: Optional[str] = None,
             features=()) -> None:
        """Open (or re-open: MERGE, never reset) ``rid``'s account —
        a failed-over / handed-off request keeps one account across
        every engine it touches."""
        acct = self._account(rid)
        if tenant is not None:
            acct["tenant"] = tenant
        acct["features"].update(features)

    def tag(self, rid: str, feature: str) -> None:
        self._account(rid)["features"].add(feature)

    def note_outcome(self, rid: str, outcome: str) -> None:
        """Record a lifecycle outcome ("completed", "shed",
        "failover", "handoff", ... — the trace-root vocabulary). A
        moved account collects the move AND its final outcome, in
        order — the exactly-once evidence chaos tests assert on."""
        self._account(rid)["outcomes"].append(outcome)

    def note_estimate(self, rid: str, units: float) -> None:
        """The admission-time estimator price (prefill + headroomed
        decode) — accumulated per rid across retries, the calibration
        signal ``tools/cost_report.py`` compares against actuals."""
        acct = self._account(rid)
        acct["est"] = (acct["est"] or 0.0) + float(units)

    # --- clock charges ----------------------------------------------------
    def _book(self, engine: str) -> dict:
        book = self._books.get(engine)
        if book is None:
            book = {"elapsed": 0, "idle": 0, "charges": {}}
            self._books[engine] = book
        return book

    def charge(self, engine: str, kind: str, delta: float, *,
               rid: Optional[str] = None,
               rids: Optional[List[str]] = None,
               weights=None) -> None:
        """Book one priced clock delta on ``engine``'s books. ``rid``
        attributes to one owner (a request, or ``"engine"`` for
        engine-owned work); ``rids`` splits pro-rata across a batched
        dispatch (by ``weights`` when the call priced per-row costs);
        neither lands in ``unattributed`` — audited to zero."""
        u = _quantize(delta)
        book = self._book(engine)
        book["elapsed"] += u
        if u == 0:
            return
        ch = book["charges"]
        if rids:
            for r, s in zip(rids, _split(u, len(rids), weights)):
                if s:
                    ch[(r, kind)] = ch.get((r, kind), 0) + s
        else:
            owner = rid if rid is not None else "unattributed"
            ch[(owner, kind)] = ch.get((owner, kind), 0) + u

    def idle(self, engine: str, delta: float) -> None:
        """Book an idle clock jump (``advance_to`` past now)."""
        u = _quantize(delta)
        book = self._book(engine)
        book["elapsed"] += u
        book["idle"] += u

    # --- occupancy integrals ----------------------------------------------
    def sample_occupancy(self, engine: str, book=None, acache=None,
                         gcache=None, arena=None) -> None:
        """One engine turn's occupancy: who held each budgeted slot
        for this turn. Pool-side integrals come from the same
        population counts ``census_ok`` checks, so the per-owner sum
        cross-checks the caches' own bookkeeping (tables vs refcounts,
        pins vs slots) — audited exact every run.

        Tiers: ``kv`` (device pages; shared prefix pages split across
        their holders, retained evictable pages owned by ``"cache"``),
        ``adapter`` / ``grammar`` (pinned slots per pin owner),
        ``host`` (arena entries per preemption owner; plain LRU spill
        owned by ``"cache"``)."""
        occ = self._occ.setdefault(engine, {})
        pool = self._occ_pool.setdefault(engine, {})
        self._turns[engine] = self._turns.get(engine, 0) + 1

        def bump(owner, tier, units):
            if units:
                occ[(owner, tier)] = occ.get((owner, tier), 0) + units

        if book is not None:
            resident, evictable, _free = book.populations()
            holders = book.page_holders()
            # aggregate unshared pages (the vast majority) into one
            # bump per holder; only shared pages need the pro-rata
            # split, and only THEY need sorting (residual-on-last
            # determinism) — additions commute
            counts: Dict[str, int] = {}
            shared = []
            for page, rids in holders.items():
                if len(rids) == 1:
                    r = rids[0]
                    counts[r] = counts.get(r, 0) + 1
                else:
                    shared.append(page)
            for r, n in counts.items():
                bump(r, "kv", n * SCALE)
            for page in sorted(shared):
                rids = holders[page]
                for r, s in zip(rids, _split_scale(len(rids))):
                    bump(r, "kv", s)
            bump("cache", "kv", evictable * SCALE)
            pool["kv"] = pool.get("kv", 0) \
                + (resident + evictable) * SCALE
        for tier, cache in (("adapter", acache), ("grammar", gcache)):
            if cache is None:
                continue
            pinned = cache.populations()[0]
            owners = cache.pin_owners()
            for name in sorted(owners):
                rids = owners[name]
                for r, s in zip(rids, _split_scale(len(rids))):
                    bump(r, tier, s)
            pool[tier] = pool.get(tier, 0) + pinned * SCALE
        if arena is not None:
            counts = arena.owner_counts()
            for owner in sorted(counts):
                bump(owner, "host", counts[owner] * SCALE)
            pool["host"] = pool.get("host", 0) \
                + sum(counts.values()) * SCALE

    # --- audits -----------------------------------------------------------
    def audit(self, engine: Optional[str] = None) -> dict:
        """The conservation audit: per engine (or every engine),
        ``sum(attributed) + idle == elapsed`` on the clock books,
        ``sum(per-owner) == pool integral`` per occupancy tier, and
        zero unattributed units. Integer arithmetic — exact, not
        tolerance-checked."""
        engines = [engine] if engine is not None \
            else sorted(set(self._books) | set(self._occ_pool))
        conserved = occupancy = True
        unattributed = 0
        for e in engines:
            book = self._books.get(e)
            if book is not None:
                attributed = sum(book["charges"].values())
                if attributed + book["idle"] != book["elapsed"]:
                    conserved = False
                unattributed += sum(
                    v for (o, _k), v in book["charges"].items()
                    if o == "unattributed")
            occ = self._occ.get(e, {})
            pool = self._occ_pool.get(e, {})
            for tier, total in pool.items():
                got = sum(v for (_o, t), v in occ.items()
                          if t == tier)
                if got != total:
                    occupancy = False
            for (_o, t) in occ:
                if t not in pool:
                    occupancy = False
        return {"conserved_ok": conserved,
                "occupancy_ok": occupancy,
                "unattributed_units": round(unattributed / SCALE, 9),
                "ok": conserved and occupancy and unattributed == 0}

    # --- views ------------------------------------------------------------
    @staticmethod
    def _units(u: int) -> float:
        return round(u / SCALE, 9)

    def cost_stats(self, engine: str) -> dict:
        """One engine's banked accounting (the ``ServeResult
        .cost_stats`` payload): the integer books in clock units, per
        kind, plus this engine's audit verdicts."""
        book = self._books.get(engine,
                               {"elapsed": 0, "idle": 0, "charges": {}})
        kinds: Dict[str, int] = {}
        for (_owner, kind), v in book["charges"].items():
            kinds[kind] = kinds.get(kind, 0) + v
        occ = self._occ.get(engine, {})
        tiers: Dict[str, int] = {}
        for (_owner, tier), v in occ.items():
            tiers[tier] = tiers.get(tier, 0) + v
        audit = self.audit(engine)
        return {
            "engine": engine,
            "elapsed_units": self._units(book["elapsed"]),
            "idle_units": self._units(book["idle"]),
            "attributed_units": self._units(
                sum(book["charges"].values())),
            "kinds": {k: self._units(v)
                      for k, v in sorted(kinds.items())},
            "page_turns": {t: self._units(v)
                           for t, v in sorted(tiers.items())},
            "turns": self._turns.get(engine, 0),
            "conserved_ok": audit["conserved_ok"],
            "occupancy_ok": audit["occupancy_ok"],
            "unattributed_units": audit["unattributed_units"],
        }

    def _request_totals(self) -> Dict[str, dict]:
        """rid -> {"units": {kind: int}, "page_turns": {tier: int}}
        summed across every engine book (system owners excluded)."""
        per: Dict[str, dict] = {}

        def row(owner):
            e = per.get(owner)
            if e is None:
                e = {"units": {}, "page_turns": {}}
                per[owner] = e
            return e

        for book in self._books.values():
            for (owner, kind), v in book["charges"].items():
                if owner in _SYSTEM_OWNERS:
                    continue
                d = row(owner)["units"]
                d[kind] = d.get(kind, 0) + v
        for occ in self._occ.values():
            for (owner, tier), v in occ.items():
                if owner in _SYSTEM_OWNERS:
                    continue
                d = row(owner)["page_turns"]
                d[tier] = d.get(tier, 0) + v
        return per

    def _features_of(self, rid: str, totals: dict) -> List[str]:
        """The account's tagged features plus the kinds-derived ones
        (a request that paid adapter_upload used lora, etc.)."""
        feats = set(self._accounts.get(rid, {}).get("features", ()))
        for kind in totals["units"]:
            f = KIND_FEATURE.get(kind)
            if f is not None:
                feats.add(f)
        if totals["page_turns"].get("host"):
            feats.add("hostmem")
        return sorted(feats)

    def tenant_costs(self) -> Dict[str, dict]:
        """tenant -> {"cost_units", "page_turns"} across every engine
        — the ``MetricsCollector.report()`` per-tenant columns.
        Untenanted requests are skipped (the QoS block only rolls up
        named tenants)."""
        out: Dict[str, dict] = {}
        for rid, tot in self._request_totals().items():
            tenant = self._accounts.get(rid, {}).get("tenant")
            if tenant is None:
                continue
            e = out.setdefault(tenant,
                               {"cost_units": 0, "page_turns": 0})
            e["cost_units"] += sum(tot["units"].values())
            e["page_turns"] += sum(tot["page_turns"].values())
        return {t: {"cost_units": self._units(v["cost_units"]),
                    "page_turns": self._units(v["page_turns"])}
                for t, v in sorted(out.items())}

    def rollup(self) -> dict:
        """The cluster-level summary (``ClusterResult.cost_rollup``):
        per-tenant and per-feature unit totals, per-engine books, the
        global audit."""
        per_req = self._request_totals()
        tenants: Dict[str, dict] = {}
        features: Dict[str, int] = {}
        for rid, tot in per_req.items():
            acct = self._accounts.get(rid, {})
            tenant = acct.get("tenant") or "-"
            te = tenants.setdefault(
                tenant, {"requests": 0, "cost_units": 0,
                         "page_turns": 0})
            te["requests"] += 1
            te["cost_units"] += sum(tot["units"].values())
            te["page_turns"] += sum(tot["page_turns"].values())
            for kind, v in tot["units"].items():
                f = KIND_FEATURE.get(kind, "base")
                features[f] = features.get(f, 0) + v
        for book in self._books.values():
            for (owner, kind), v in book["charges"].items():
                if owner in _SYSTEM_OWNERS:
                    f = KIND_FEATURE.get(kind, "base")
                    features[f] = features.get(f, 0) + v
        audit = self.audit()
        return {
            "requests": len(per_req),
            "tenants": {
                t: {"requests": e["requests"],
                    "cost_units": self._units(e["cost_units"]),
                    "page_turns": self._units(e["page_turns"])}
                for t, e in sorted(tenants.items())},
            "features": {f: self._units(v)
                         for f, v in sorted(features.items())},
            "engines": {e: self.cost_stats(e)
                        for e in sorted(self._books)},
            **audit,
        }

    # --- artifacts --------------------------------------------------------
    def save_costs(self, path: str) -> str:
        """Dump the ledger as JSONL (atomic, the shared ``obs`` write
        discipline): per-request rows, per-tenant rows, per-feature
        rows, per-engine rows — and the global audit row LAST (the
        report-tool convention)."""
        rows: List[dict] = []
        per_req = self._request_totals()
        for rid in sorted(per_req):
            tot = per_req[rid]
            acct = self._accounts.get(rid, {})
            row = {"row": "request", "rid": rid,
                   "tenant": acct.get("tenant"),
                   "features": self._features_of(rid, tot),
                   "units": {k: self._units(v) for k, v
                             in sorted(tot["units"].items())},
                   "total_units": self._units(
                       sum(tot["units"].values())),
                   "page_turns": {t: self._units(v) for t, v
                                  in sorted(tot["page_turns"].items())},
                   "outcomes": list(acct.get("outcomes", []))}
            if acct.get("est") is not None:
                row["est_units"] = round(acct["est"], 9)
            rows.append(row)
        roll = self.rollup()
        for tenant, e in roll["tenants"].items():
            rows.append({"row": "tenant", "tenant": tenant, **e})
        for feat, v in roll["features"].items():
            rows.append({"row": "feature", "feature": feat,
                         "cost_units": v})
        for engine, stats in roll["engines"].items():
            rows.append({"row": "engine", **stats})
        rows.append({"row": "global",
                     "requests": roll["requests"],
                     "cost_units": self._units(sum(
                         sum(b["charges"].values())
                         for b in self._books.values())),
                     "conserved_ok": roll["conserved_ok"],
                     "occupancy_ok": roll["occupancy_ok"],
                     "unattributed_units": roll["unattributed_units"],
                     "ok": roll["ok"]})
        _atomic_write(path, "".join(json.dumps(r) + "\n"
                                    for r in rows))
        return path

    def publish(self, registry) -> None:
        """Export the books into the metrics registry (armed-only —
        the caller guards, so a ledger-less run's registry stays
        byte-identical): ``serving_cost_units_total{tenant,kind}`` and
        ``serving_page_turns_total{tenant,tier}``. Watermarked: safe
        to call once per session on a shared ledger — each call
        increments by the delta since the last publish."""
        def bump(name, help_, key, value, **labels):
            prev = self._published.get(key, 0)
            if value > prev:
                registry.counter(name, help_, **labels).inc(
                    (value - prev) / SCALE)
                self._published[key] = value

        units: Dict[Tuple[str, str], int] = {}
        for book in self._books.values():
            for (owner, kind), v in book["charges"].items():
                if owner in _SYSTEM_OWNERS:
                    tenant = owner
                else:
                    tenant = self._accounts.get(owner, {}) \
                                 .get("tenant") or "-"
                key = (tenant, kind)
                units[key] = units.get(key, 0) + v
        for (tenant, kind) in sorted(units):
            bump("serving_cost_units_total",
                 "attributed virtual-clock cost units",
                 ("u", tenant, kind), units[(tenant, kind)],
                 tenant=tenant, kind=kind)
        turns: Dict[Tuple[str, str], int] = {}
        for occ in self._occ.values():
            for (owner, tier), v in occ.items():
                if owner in _SYSTEM_OWNERS:
                    tenant = owner
                else:
                    tenant = self._accounts.get(owner, {}) \
                                 .get("tenant") or "-"
                key = (tenant, tier)
                turns[key] = turns.get(key, 0) + v
        for (tenant, tier) in sorted(turns):
            bump("serving_page_turns_total",
                 "pool slot-turns held (pages x engine turns)",
                 ("t", tenant, tier), turns[(tenant, tier)],
                 tenant=tenant, tier=tier)


def load_costs(path: str) -> List[dict]:
    """Read a ``save_costs`` JSONL back (tolerant: blank lines
    skipped), for the report tools."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows
