"""paddle_tpu.obs — the observability spine: request-scoped tracing +
a process-global metrics registry.

Two halves, both dependency-free and import-light (no jax):

- ``obs.trace``: ``Tracer`` (tracks, nested spans, async request
  lifecycles, chrome://tracing export), a process-global active
  tracer for layers that cannot be handed one (the jit program cache,
  ``route_decode``), and a ``trace_id`` contextvar tying spans to the
  request that caused them. ``ServingEngine(trace=...)`` threads one
  through the serving lifecycle; ``tools/trace_report.py`` summarizes
  the export (per-request waterfall, top recompiles, shed timeline,
  slot occupancy).
- ``obs.metrics``: counters / gauges / fixed-bucket histograms with
  Prometheus text exposition (``REGISTRY.expose_text()``) and JSONL
  snapshots (``REGISTRY.write_jsonl(path)``). Counters stay live even
  when no trace records; ``REGISTRY.disable()`` is the no-obs
  baseline arm of ``tools/bench_gate.py obs`` (tracing-off overhead
  gated <= 2% on the serving workload bench).

Two ACTIVE halves evaluate those streams (PR 9):

- ``obs.slo``: declarative SLO rules (threshold, multi-window
  burn-rate over an error budget, heartbeat silence) evaluated
  STREAMING on the virtual clock by ``SLOMonitor``, firing typed
  ``Incident`` objects into a shareable ``IncidentLog`` (JSONL,
  deterministic ids). ``ServingEngine(slo=...)`` and
  ``ClusterRouter(slo=...)`` thread monitors through the serving
  stack; ``tools/slo_report.py`` renders the incident timeline and
  per-rule budget burn-down.
- ``obs.flight``: the incident flight recorder — an always-on bounded
  ring of recent trace events (via the Tracer mirror sink) + metric
  samples that freezes a deterministic postmortem bundle
  (chrome-trace excerpt, metrics JSONL, incident JSON, offending
  rids) the moment an incident fires.

And the ACCOUNTING half (PR 19):

- ``obs.ledger``: the resource-attribution ledger — ``CostLedger``
  books every priced virtual-clock unit against ``(rid | "engine",
  kind)`` and per-turn pool occupancy against its holders, rolled up
  request -> tenant -> feature, with exact integer conservation
  audits (``attributed + idle == elapsed``; per-owner slot-turns ==
  pool integral). Also the shared budgeted-cache census arithmetic
  (``census_balanced`` / ``overlay_contained``) the four pool
  ``census_ok()`` checks delegate to. ``ServingEngine(ledger=...)``
  and ``ClusterRouter(cost_ledger=...)`` thread one through;
  ``tools/cost_report.py`` renders the tables.

Span taxonomy, metric names, the SLO rule grammar / burn-rate math /
bundle layout and the Perfetto how-to live in docs/OBSERVABILITY.md.
"""
from . import flight, ledger, metrics, slo, trace  # noqa: F401
from .ledger import (SCALE, CostLedger,  # noqa: F401
                     census_balanced, load_costs, overlay_contained)
from .flight import FlightRecorder, load_bundle  # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge,  # noqa: F401
                      Histogram, MetricsRegistry, get_registry)
from .slo import (BurnRateRule, HeartbeatRule,  # noqa: F401
                  Incident, IncidentLog, SLOMonitor, ThresholdRule,
                  default_serving_rules, load_incidents)
from .trace import (Tracer, activate, active,  # noqa: F401
                    deactivate, get_trace_id, trace_scope, use)
