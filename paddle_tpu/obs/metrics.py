"""Process-global metrics registry: counters, gauges, fixed-bucket
histograms; Prometheus text exposition + JSONL snapshots. No deps.

The numeric half of the observability layer (the tracing half is
``obs.trace``): long-lived process aggregates that answer "how many /
how much / how long, ever" where a trace answers "what happened to
THIS request". Instrumented call sites (the serving engine, the jit
program cache, ``route_decode``) call ``REGISTRY.counter(...).inc()``
unconditionally; the registry's ``enabled`` flag turns every mutation
into one attribute check + return, which is what the ``obs_overhead``
bench gate prices (tools/bench_gate.py obs: tracing-off overhead on
the serving workload must stay <= 2%).

Naming follows the Prometheus conventions the exposition format
implies: ``*_total`` for counters, ``*_seconds`` for durations,
labels for low-cardinality dimensions (a routing rule, a backend —
never a request id).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

# latency-shaped default buckets (seconds), Prometheus-style
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(items: Iterable[Tuple[str, str]]) -> str:
    body = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + body + "}" if body else ""


class _Metric:
    __slots__ = ("name", "labels", "_reg")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 reg: "MetricsRegistry"):
        self.name = name
        self.labels = labels
        self._reg = reg


class Counter(_Metric):
    """Monotonic count. ``inc`` is the hot-path call: one enabled
    check, one add."""

    __slots__ = ("value",)

    def __init__(self, name, labels, reg):
        super().__init__(name, labels, reg)
        self.value = 0.0

    def inc(self, n: float = 1.0):
        if not self._reg.enabled:
            return
        if n < 0:
            raise ValueError("counters only go up (use a gauge)")
        self.value += n


class Gauge(_Metric):
    """A value that goes up and down (queue depth, cache size)."""

    __slots__ = ("value",)

    def __init__(self, name, labels, reg):
        super().__init__(name, labels, reg)
        self.value = 0.0

    def set(self, v: float):
        if self._reg.enabled:
            self.value = float(v)

    def inc(self, n: float = 1.0):
        if self._reg.enabled:
            self.value += n

    def dec(self, n: float = 1.0):
        self.inc(-n)


class Histogram(_Metric):
    """Fixed upper-bound buckets (cumulative at exposition), plus
    running sum/count — enough for rate + quantile-bound queries
    without reservoirs or deps."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, name, labels, reg, buckets=None):
        super().__init__(name, labels, reg)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        self.counts = [0] * len(bs)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float):
        if not self._reg.enabled:
            return
        v = float(v)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        # above every bound: lands only in the implicit +Inf bucket

    def cumulative(self):
        """[(le, cumulative_count)] including +Inf, exposition order."""
        out, c = [], 0
        for b, n in zip(self.buckets, self.counts):
            c += n
            out.append((b, c))
        out.append((float("inf"), self.count))
        return out


class MetricsRegistry:
    """Get-or-create registry keyed by (name, sorted labels). One
    process-global instance (``REGISTRY``); tests construct private
    ones. ``disable()`` is the kill switch the no-obs baseline arm of
    the overhead bench runs under."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[tuple, _Metric] = {}
        self._types: Dict[str, type] = {}
        self._help: Dict[str, str] = {}
        self.enabled = True

    # --- registration -----------------------------------------------------
    def _get(self, cls, name: str, help_: str, labels: dict,
             **kw) -> _Metric:
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(f"{name}: already registered as "
                                 f"{type(m).__name__}")
            return m
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                prev = self._types.get(name)
                if prev is not None and prev is not cls:
                    raise ValueError(f"{name}: already registered as "
                                     f"{prev.__name__}")
                m = cls(name, key[1], self, **kw)
                self._metrics[key] = m
                self._types[name] = cls
                if help_:
                    self._help[name] = help_
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[tuple] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    # --- lifecycle --------------------------------------------------------
    def enable(self):
        self.enabled = True

    def disable(self):
        """Every subsequent inc/set/observe becomes a no-op (the
        registry keeps its metrics; re-enable resumes accumulation)."""
        self.enabled = False

    def reset(self):
        with self._lock:
            self._metrics.clear()
            self._types.clear()
            self._help.clear()

    # --- exposition -------------------------------------------------------
    def expose_text(self) -> str:
        """Prometheus text exposition format (families sorted by name,
        children by label string — deterministic output)."""
        by_name: Dict[str, list] = {}
        for (name, _), m in self._metrics.items():
            by_name.setdefault(name, []).append(m)
        lines = []
        for name in sorted(by_name):
            cls = self._types[name]
            kind = {"Counter": "counter", "Gauge": "gauge",
                    "Histogram": "histogram"}[cls.__name__]
            if name in self._help:
                lines.append(f"# HELP {name} {self._help[name]}")
            lines.append(f"# TYPE {name} {kind}")
            for m in sorted(by_name[name], key=lambda m: m.labels):
                lab = _fmt_labels(m.labels)
                if isinstance(m, Histogram):
                    for le, c in m.cumulative():
                        le_s = "+Inf" if le == float("inf") else \
                            format(le, "g")
                        items = m.labels + (("le", le_s),)
                        lines.append(f"{name}_bucket"
                                     f"{_fmt_labels(items)} {c}")
                    lines.append(f"{name}_sum{lab} "
                                 f"{format(m.sum, 'g')}")
                    lines.append(f"{name}_count{lab} {m.count}")
                else:
                    lines.append(f"{name}{lab} {format(m.value, 'g')}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """One JSON-ready dict: metric name + label string -> value
        (histograms -> {sum, count, buckets})."""
        out = {}
        for (name, labels), m in sorted(self._metrics.items()):
            key = name + _fmt_labels(labels)
            if isinstance(m, Histogram):
                out[key] = {"sum": m.sum, "count": m.count,
                            "buckets": {format(b, "g"): c
                                        for b, c in m.cumulative()
                                        if b != float("inf")},
                            "inf": m.count}
            else:
                out[key] = m.value
        return out

    def write_jsonl(self, path: str, **extra) -> dict:
        """Append one snapshot line (wall-stamped) — the scrape-to-file
        analog of a Prometheus pull."""
        rec = {"ts": round(time.time(), 3), **extra,
               "metrics": self.snapshot()}
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec


REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


def counter(name: str, help: str = "", **labels) -> Counter:
    return REGISTRY.counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return REGISTRY.gauge(name, help, **labels)


def histogram(name: str, help: str = "", buckets=None,
              **labels) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets, **labels)
