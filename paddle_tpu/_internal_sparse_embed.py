"""Sparse-gradient embedding lookup (SelectedRows producer).

Split out of nn.functional to keep the tape wiring in one place: the
lookup bypasses apply_op (jax.vjp only moves arrays) and records a
hand-built GradNode whose weight cotangent is a SelectedRows — mirroring
the reference's codegened lookup_table_v2_grad op that emits a
SelectedRows when is_sparse=True (fluid/operators/lookup_table_v2_op.cc).
"""
from __future__ import annotations

import jax.numpy as jnp

from .autograd import tape as _tape
from .core.selected_rows import SelectedRows
from .core.tensor import Tensor


def maybe_sparse_embedding(x, weight, padding_idx, sparse):
    """Returns the lookup Tensor with sparse grad recording, or None to
    fall through to the dense apply_op path (static capture, no-grad,
    sparse=False)."""
    if not sparse:
        return None
    if getattr(x, "_symbolic", False) or getattr(weight, "_symbolic", False):
        return None  # static capture keeps the dense program form
    ids = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    wv = weight._value
    out = jnp.take(wv, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    grad_wanted = (_tape.grad_enabled() and isinstance(weight, Tensor)
                   and not weight.stop_gradient)
    t = Tensor(out, stop_gradient=not grad_wanted)
    if not grad_wanted:
        return t

    V, H = wv.shape
    flat_ids = ids.reshape(-1)
    if padding_idx is not None:
        # ids are concrete in this eager path: drop padding entries with a
        # STATIC index set, so no row (not even row 0) is spuriously
        # touched by moment-carrying/weight-decaying lazy optimizers
        import numpy as np
        keep_idx = jnp.asarray(
            np.flatnonzero(np.asarray(flat_ids) != padding_idx), jnp.int32)
    else:
        keep_idx = None

    def vjp_fn(ct):
        vals = ct.reshape(-1, H).astype(jnp.float32)
        rows = flat_ids
        if keep_idx is not None:
            vals = vals[keep_idx]
            rows = rows[keep_idx]
        return (SelectedRows(rows, vals, height=V),)

    node = _tape.GradNode("sparse_embedding", vjp_fn, inputs=[weight],
                          out_avals=[(tuple(out.shape), out.dtype)])
    t._grad_node = node
    t._output_index = 0
    return t
